"""Scenario registry for the controlled scheduler (analysis.sched).

A scenario is a plain callable run under one controlled schedule: the
explorer calls it N times with N different seeded schedules, each
inside a fresh strict-free hb shim (so FastTrack race detection rides
every schedule) with the scheduler installed at the shim's yield
points.  Scenarios must therefore be:

* self-contained — construct every server/store/lane INSIDE the call
  (the shim only instruments locks born inside the block);
* re-runnable — tear everything down in ``finally`` even when a
  schedule aborts (the scheduler unwinds threads with ``SchedAbort``);
* self-checking — assert their arithmetic: a scenario exception is a
  FINDING (the check-then-act seeded bug is caught exactly this way).

The seven REAL scenarios are the distributed plane's most
schedule-sensitive flows (the five test_hb.py acceptance scenarios
plus the shmlane ring collapse and the acceptor-pool collect parking);
the two BUG scenarios are deliberately planted concurrency bugs —
a two-lock ABBA deadlock and a check-then-act atomicity race — that
survive free-running execution (see tests/test_sched.py) and exist to
prove the explorer finds what the OS scheduler doesn't.

Add a scenario::

    @register("my_scenario", env={"MXNET_...": "1"})
    def _sc_my_scenario():
        ...build, run, assert, tear down...

and it is reachable via ``python -m mxnet_tpu.analysis --explore
my_scenario`` and picked up by the CI explorer gate.
"""
from __future__ import annotations

import contextlib
import os
import socket
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import numpy as np

from . import hb

__all__ = ["Scenario", "register", "get", "names", "REAL", "BUGS",
           "deadlock_once", "atomicity_once"]


class Scenario:
    """A registered scenario: the callable plus the env overlay the
    explorer applies around the shim (static knobs only — dynamic
    values like ports are set inside the callable)."""

    def __init__(self, name: str, fn: Callable[[], None],
                 env: Optional[Dict[str, str]], kind: str, doc: str,
                 lease_s: float = 0.5):
        self.name = name
        self.fn = fn
        self.env = dict(env or {})
        self.kind = kind          # "real" | "bug"
        self.doc = doc
        # How long the scheduler lets the token holder run outside the
        # model (real socket IO, compute) before leasing the token away.
        # Socket-heavy scenarios set this low: every blocking recv while
        # holding the token costs one full lease, so 0.5s leases make a
        # heartbeat-driven scenario crawl at ~2 decisions/s.
        self.lease_s = float(lease_s)


_REGISTRY: "OrderedDict[str, Scenario]" = OrderedDict()


def register(name: str, env: Optional[Dict[str, str]] = None,
             kind: str = "real", lease_s: float = 0.5):
    def deco(fn):
        _REGISTRY[name] = Scenario(name, fn, env, kind,
                                   (fn.__doc__ or "").strip(),
                                   lease_s=lease_s)
        return fn
    return deco


def get(name: str) -> Scenario:
    sc = _REGISTRY.get(name)
    if sc is None:
        raise KeyError("unknown scenario %r (have: %s)"
                       % (name, ", ".join(_REGISTRY)))
    return sc


def names(kind: Optional[str] = None) -> List[str]:
    return [n for n, sc in _REGISTRY.items()
            if kind is None or sc.kind == kind]


@contextlib.contextmanager
def _envctx(**kv):
    """Scoped os.environ overlay for DYNAMIC values (ports picked at
    run time); the static per-scenario env rides Scenario.env."""
    saved = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# the seeded bugs (exported plain so tests can free-run them WITHOUT
# the scheduler and show they survive hundreds of iterations)
# ---------------------------------------------------------------------------
def deadlock_once(join_timeout: Optional[float] = None) -> bool:
    """One round of the planted ABBA deadlock: two threads take two
    locks in opposite orders with a tracked-dict touch between the
    acquisitions (a few microseconds free-running — the OS essentially
    never preempts inside it; one PCT priority change always can).
    Returns True when the round deadlocked (threads still alive after
    ``join_timeout``); under the controlled scheduler the untimed
    joins let the deadlock detector fire instead."""
    la, lb = threading.Lock(), threading.Lock()
    d = hb.track({}, "bug.deadlock.step")

    def ab():
        with la:
            d["ab"] = 1
            with lb:
                d["ab"] = 2

    def ba():
        with lb:
            d["ba"] = 1
            with la:
                d["ba"] = 2

    # analysis: allow(bare-thread): planted-bug threads — their death OR hang is the observed outcome (joined with a timeout; the deadlock detector watches them under the scheduler)
    ts = [threading.Thread(target=ab, name="ab"),
          # analysis: allow(bare-thread): planted-bug thread — see 'ab' above
          threading.Thread(target=ba, name="ba")]
    for t in ts:
        t.start()
    for t in ts:
        t.join(join_timeout)
    return any(t.is_alive() for t in ts)


def atomicity_once() -> int:
    """One round of the planted check-then-act race: a balance of 1,
    two withdrawers, every ACCESS individually locked (so there is no
    data race for the hb sanitizer to flag) — but the check and the
    act are separate critical sections, and a preemption in between
    lets both threads see the 1 and both withdraw.  Returns the final
    balance; the caller asserts it never went negative."""
    lock = threading.Lock()
    bal = hb.track({"v": 1}, "bug.balance")

    def withdraw():
        with lock:
            ok = bal["v"] >= 1
        if ok:
            with lock:
                bal["v"] -= 1

    # analysis: allow(bare-thread): planted-bug threads — both are joined untimed and the caller's balance assertion is the failure detector
    ts = [threading.Thread(target=withdraw, name="w%d" % i)
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return bal["v"]


@register("bug_deadlock", kind="bug")
def _sc_bug_deadlock():
    """Planted ABBA deadlock (two rounds per schedule — each an
    independent chance for the priority change to land inside the
    lock-order window)."""
    for _ in range(2):
        deadlock_once(join_timeout=None)


@register("bug_atomicity", kind="bug")
def _sc_bug_atomicity():
    """Planted check-then-act overdraw; the assertion failure becomes
    a scenario-error finding."""
    for _ in range(2):
        v = atomicity_once()
        assert v >= 0, "balance overdrawn to %d: check-then-act " \
                       "withdraw is not atomic" % v


# ---------------------------------------------------------------------------
# the seven real scenarios
# ---------------------------------------------------------------------------
@register("kill_replay", lease_s=0.05, env={
    "MXNET_KVSTORE_RETRY_MAX": "8",
    "MXNET_KVSTORE_RETRY_INITIAL_MS": "10",
    "MXNET_KVSTORE_RETRY_MAX_MS": "50",
    "MXNET_KVSTORE_HEARTBEAT_INTERVAL": "0",
    "MXNET_KVSTORE_WINDOW": "4",
    "DMLC_NUM_WORKER": "1",
    "DMLC_WORKER_ID": "0",
})
def _sc_kill_replay():
    """Pipelined pushes, mid-window connection kill, full-window
    replay against the server dedup — arithmetic must stay exact under
    every schedule (a double-apply or a lost push moves the sum)."""
    import mxnet_tpu as mx
    from mxnet_tpu import faultinject
    from mxnet_tpu.kvstore_server import KVStoreServer
    faultinject.reset()
    shape = (2, 3)
    srv = KVStoreServer(server_id=0, num_workers=1)
    srv.start_background()
    try:
        with _envctx(MXT_SERVER_URIS="127.0.0.1:%d" % srv.port):
            kv = mx.kv.create("dist_async")
            kv.init("w", mx.nd.ones(shape))
            kv.set_optimizer(mx.optimizer.SGD(
                learning_rate=0.5, momentum=0.0, wd=0.0,
                rescale_grad=1.0))
            out = mx.nd.zeros(shape)
            with faultinject.delay_acks(0.05):
                with faultinject.kill_when_unacked(2):
                    for i in range(3):
                        kv.push("w", mx.nd.ones(shape) * (i + 1))
                    kv.pull("w", out=out)
            # 1+2+3 applied exactly once each regardless of the kill
            np.testing.assert_allclose(out.asnumpy(), 1.0 - 0.5 * 6,
                                       rtol=1e-6)
            kv.close(stop_servers=True)
    finally:
        srv.stop()
        faultinject.reset()


_ELASTIC_ENV = {
    "MXNET_KVSTORE_ELASTIC": "1",
    # a schedule can legally park the client across the whole
    # stop->promote window, so give reconnects more headroom than the
    # free-running test_hb variants need
    "MXNET_KVSTORE_RETRY_MAX": "8",
    "MXNET_KVSTORE_RETRY_INITIAL_MS": "10",
    "MXNET_KVSTORE_RETRY_MAX_MS": "50",
    "MXNET_KVSTORE_HEARTBEAT_INTERVAL": "0.1",
    "MXNET_KVSTORE_HEARTBEAT_TIMEOUT": "0.5",
    "MXNET_KVSTORE_BIGARRAY_BOUND": "16",
    "MXNET_KVSTORE_SNAPSHOT_S": "0.0",
    "DMLC_NUM_WORKER": "1",
    "DMLC_WORKER_ID": "0",
}


@contextlib.contextmanager
def _elastic_pair():
    from mxnet_tpu.kvstore_server import KVStoreServer
    srv0 = KVStoreServer(server_id=0, num_workers=1, elastic=True)
    srv1 = KVStoreServer(server_id=1, num_workers=1, elastic=True)
    uris = "127.0.0.1:%d,127.0.0.1:%d" % (srv0.port, srv1.port)
    srv0._roster_servers = uris.split(",")
    srv1._roster_servers = uris.split(",")
    try:
        with _envctx(MXT_SERVER_URIS=uris):
            srv0.start_background()
            srv1.start_background()
            yield srv0, srv1
    finally:
        srv0.stop()
        srv1.stop()


@register("handoff", env=_ELASTIC_ENV, lease_s=0.05)
def _sc_handoff():
    """Kill a striped elastic server mid-training and ride the
    three-phase handoff (quorum re-push, restripe, orphan re-push)."""
    import mxnet_tpu as mx
    with _elastic_pair() as (srv0, srv1):
        kv = mx.kv.create("dist_async")
        big = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("big", mx.nd.NDArray(big))
        kv.init("small", mx.nd.ones((2, 2)))
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=0.125, momentum=0.0, wd=0.0, rescale_grad=1.0))
        kv.push("big", mx.nd.ones((10, 4)))
        kv.push("small", mx.nd.ones((2, 2)))
        out_b, out_s = mx.nd.zeros((10, 4)), mx.nd.zeros((2, 2))
        kv.pull("big", out=out_b)
        kv.pull("small", out=out_s)
        srv1.stop()
        kv.push("big", mx.nd.ones((10, 4)) * 2)
        kv.push("small", mx.nd.ones((2, 2)) * 2)
        kv.barrier()
        kv.pull("big", out=out_b)
        kv.pull("small", out=out_s)
        np.testing.assert_array_equal(out_b.asnumpy(), big - 0.125 * 3)
        np.testing.assert_array_equal(out_s.asnumpy(), 1.0 - 0.125 * 3)
        kv.close(stop_servers=True)


@register("failover", env=_ELASTIC_ENV, lease_s=0.05)
def _sc_failover():
    """Kill the COORDINATOR: succession election, ledger rebuild,
    idempotent barrier retry against the successor."""
    import mxnet_tpu as mx
    with _elastic_pair() as (srv0, srv1):
        kv = mx.kv.create("dist_async")
        big = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("big", mx.nd.NDArray(big))
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=0.125, momentum=0.0, wd=0.0, rescale_grad=1.0))
        kv.push("big", mx.nd.ones((10, 4)))
        out_b = mx.nd.zeros((10, 4))
        kv.pull("big", out=out_b)
        srv0.stop()
        kv.push("big", mx.nd.ones((10, 4)) * 2)
        kv.barrier()
        kv.pull("big", out=out_b)
        np.testing.assert_array_equal(out_b.asnumpy(), big - 0.125 * 3)
        assert srv1._promoted
        kv.close(stop_servers=True)


@register("replan", env=_ELASTIC_ENV, lease_s=0.05)
def _sc_replan():
    """A striped pull in flight when its server dies: wait() repairs
    the roster and re-issues the unserved tail (values exact; whether
    THIS schedule needed the replan is timing-dependent — the
    deterministic count assertion lives in test_hb.py)."""
    import mxnet_tpu as mx
    from mxnet_tpu import faultinject, membership
    i = 0
    while True:
        small = "sm%d" % i
        if membership.server_index(small, 2) == 0 \
                and membership.server_index(small, 1) == 0:
            break
        i += 1
    big0 = np.arange(40, dtype=np.float32).reshape(10, 4)
    with _elastic_pair() as (srv0, srv1):
        kv = mx.kv.create("dist_async")
        assert kv._stripe_plan("big", (10, 4)) is not None
        kv.init("big", mx.nd.NDArray(big0))
        kv.init(small, mx.nd.ones((2, 2)))
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=0.125, momentum=0.0, wd=0.0, rescale_grad=1.0))
        kv.push("big", mx.nd.ones((10, 4)))
        kv.push(small, mx.nd.ones((2, 2)))
        out_b, out_s = mx.nd.zeros((10, 4)), mx.nd.zeros((2, 2))
        kv.pull("big", out=out_b)
        kv.pull(small, out=out_s)
        with faultinject.delay_acks(0.3):
            handle = kv.pull_async(["big", small], [(10, 4), (2, 2)])
            time.sleep(0.05)
            srv1.stop()
            vals = handle.wait()
        np.testing.assert_array_equal(vals["big"], big0 - 0.125)
        np.testing.assert_array_equal(vals[small], 1.0 - 0.125)
        kv.close(stop_servers=True)
    faultinject.reset()


def _mesh_scenario(n_ranks, steps, extra_env):
    import mxnet_tpu as mx
    from mxnet_tpu.kvstore import KVStoreDistAsync
    from mxnet_tpu.kvstore_server import KVStoreServer
    SHAPE, LR = (4, 4), 0.25

    def grad(rank, step):
        rs = np.random.RandomState(100 * rank + step)
        return rs.randint(-2, 3, SHAPE).astype(np.float32)

    w0 = np.arange(np.prod(SHAPE), dtype=np.float32).reshape(SHAPE)
    results, errors = {}, []
    env = {"DMLC_NUM_WORKER": str(n_ranks), "DMLC_WORKER_ID": "0",
           "MXNET_KVSTORE_HIERARCHY": "1",
           "MXNET_KVSTORE_WORKERS_PER_HOST": str(n_ranks),
           "MXT_MESH_URIS": "127.0.0.1:%d" % _free_port()}
    env.update(extra_env)
    with _envctx(**env):
        srv = KVStoreServer(server_id=0, num_workers=n_ranks)
        srv.start_background()
        try:
            with _envctx(MXT_SERVER_URIS="127.0.0.1:%d" % srv.port):

                def worker(rank, kv):
                    try:
                        kv.init("w", mx.nd.NDArray(w0))
                        kv.set_optimizer(mx.optimizer.SGD(
                            learning_rate=LR, momentum=0.0, wd=0.0,
                            rescale_grad=1.0))
                        kv.barrier()
                        out = mx.nd.zeros(SHAPE)
                        for s in range(steps):
                            kv.push("w", mx.nd.NDArray(grad(rank, s)))
                            kv.pull("w", out=out)
                        kv.barrier()
                        kv.pull("w", out=out)
                        results[rank] = out.asnumpy().copy()
                    except BaseException as exc:  # noqa: BLE001 — to main
                        errors.append((rank, exc))
                        raise

                kv0 = KVStoreDistAsync(rank=0)   # leader binds the mesh
                kvs = [kv0] + [KVStoreDistAsync(rank=r)
                               for r in range(1, n_ranks)]
                threads = [threading.Thread(target=worker, args=(r, kv))
                           for r, kv in enumerate(kvs)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=120)
                assert not errors, errors
                assert all(not t.is_alive() for t in threads), \
                    "worker hung"
                expected = w0.copy()
                for s in range(steps):
                    expected = expected - np.float32(LR) * sum(
                        grad(r, s) for r in range(n_ranks))
                for r in range(n_ranks):
                    np.testing.assert_array_equal(results[r], expected)
                for kv in kvs[1:]:
                    kv.close()
                kv0.close(stop_servers=True)
        finally:
            srv.stop()


@register("mesh_fanin", lease_s=0.05)
def _sc_mesh_fanin():
    """Hierarchical mesh fan-in: leader + follower reduce in-mesh and
    resolve the same wire round through the leader's handle."""
    _mesh_scenario(n_ranks=2, steps=2, extra_env={})


@register("shm_ring")
def _sc_shm_ring():
    """Shmlane SPSC ring producer/consumer, then the stall-watchdog
    collapse: the consumer stops draining, the producer detects the
    stall and marks the lane dead; a dead lane refuses traffic."""
    from mxnet_tpu import shmlane
    lane = shmlane.ShmLane.create(8 * 1024)
    got: list = []
    try:
        def consumer():
            while len(got) < 6:
                msg = lane.recv_request()
                if msg is None:
                    time.sleep(0.001)
                    continue
                got.append(msg["i"])

        # the ring is SPSC and the sanitizer holds it to ONE writer
        # thread per index for the lane's whole lifetime — so the main
        # thread is the producer for BOTH phases (a thread-per-phase
        # producer is itself a single-writer violation, and the
        # explorer flags it)
        # analysis: allow(bare-thread): scenario thread — joined untimed right below; a crash leaves got short and fails the FIFO assertion loudly
        t = threading.Thread(target=consumer, name="ring-cons")
        t.start()
        for i in range(6):
            while not lane.send_request({"i": i}):
                time.sleep(0.001)
        t.join()
        assert got == list(range(6)), got   # SPSC: FIFO, no loss
        # phase 2: nobody drains — the producer's watchdog collapses
        # the lane instead of wedging forever
        assert lane.send_request({"i": 99})
        deadline = time.monotonic() + 30
        while not lane.drain_stalled(0.05):
            assert time.monotonic() < deadline, "stall never detected"
            time.sleep(0.01)
        lane.mark_dead()
        assert lane.dead()
        assert not lane.send_request({"i": 100}), \
            "dead lane accepted traffic"
    finally:
        lane.destroy()


@register("acceptor_park", lease_s=0.05, env={
    "MXNET_KVSTORE_MESH_ACCEPTORS": "1",
    "MXNET_KVSTORE_MESH_FANIN_S": "30",
})
def _sc_acceptor_park():
    """Acceptor-pool collect parking: two followers on ONE pool thread
    send their round-0 mesh_collect BEFORE the leader registered the
    round — both must park in the worker's pending list (blocking the
    thread would starve the mesh_push it is also serving) and be
    served when the leader publishes the handle."""
    from mxnet_tpu.kvstore import _MeshLeader
    from mxnet_tpu.kvstore_server import _recv_msg, _send_msg
    port = _free_port()
    leader = _MeshLeader("127.0.0.1:%d" % port, n_followers=2)
    replies: dict = {}
    errors: list = []
    try:
        def follower(rank):
            try:
                s = socket.create_connection(("127.0.0.1", port),
                                             timeout=30)
                try:
                    g = np.full((2, 2), float(rank + 1),
                                dtype=np.float32)
                    cid = (rank, "park")
                    # analysis: allow(raw-send): the POINT of this scenario is hand-rolled follower frames hitting the acceptor before the leader registers the round — the envelope client would serialize exactly the ordering under test
                    _send_msg(s, ("req", cid, 0,
                                  ("mesh_push", 0, [("w", g)])),
                              byte_kind="ici_sent")
                    # analysis: allow(raw-send): see the mesh_push frame above
                    st, _ = _recv_msg(s, byte_kind="ici_recv")
                    assert st == "ok"
                    # analysis: allow(raw-send): see the mesh_push frame above
                    _send_msg(s, ("req", cid, 1,
                                  ("mesh_collect", 0, ["w"])),
                              byte_kind="ici_sent")
                    # analysis: allow(raw-send): see the mesh_push frame above
                    st, vals = _recv_msg(s, byte_kind="ici_recv")
                    assert st == "ok", vals
                    replies[rank] = np.asarray(vals["w"])
                finally:
                    s.close()
            except BaseException as exc:  # noqa: BLE001 — to main
                errors.append((rank, exc))
                raise

        ts = [threading.Thread(target=follower, args=(r,),
                               name="follower-%d" % r) for r in (0, 1)]
        for t in ts:
            t.start()
        pairs = leader.collect_push(0)    # fan-in: both rounds arrive
        assert len(pairs) == 2, pairs
        summed = sum(np.asarray(g) for plist in pairs
                     for _, g in plist)

        class _Handle:
            def wait(self):
                return {"w": summed}

        leader.publish_handle(0, _Handle())
        for t in ts:
            t.join(timeout=60)
        assert not errors, errors
        assert all(not t.is_alive() for t in ts), "follower hung"
        for r in (0, 1):
            np.testing.assert_array_equal(replies[r], summed)
        np.testing.assert_array_equal(
            summed, np.full((2, 2), 3.0, dtype=np.float32))
    finally:
        leader.close()


REAL = names("real")
BUGS = names("bug")
