"""Shared directed-graph reachability/cycle helpers.

One implementation for both halves of the lock-order story — the
static rule (:mod:`mxnet_tpu.analysis.rules.lock_order`) and the
runtime sanitizer (:mod:`mxnet_tpu.analysis.runtime`) — so a
hardening fix (iterative DFS, cycle-path reporting) can never apply to
one and silently miss the other.  ``adj`` is ``{node: iterable of
successor nodes}``; absent keys mean no successors.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional


def reaches(adj: Dict[str, Iterable[str]], src: str, dst: str) -> bool:
    """True when a directed path src -> ... -> dst exists (src == dst
    counts: the empty path)."""
    seen, stack = set(), [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(adj.get(n, ()))
    return False


def find_cycle(adj: Dict[str, Iterable[str]]) -> Optional[List[str]]:
    """A cycle as a node list ``[a, b, ..., a]``, or None when acyclic.
    Iterative coloring DFS — safe on graphs deeper than the recursion
    limit."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    for root in adj:
        if color.get(root, WHITE) != WHITE:
            continue
        path = []
        stack = [(root, iter(adj.get(root, ())))]
        color[root] = GREY
        path.append(root)
        while stack:
            node, succs = stack[-1]
            advanced = False
            for m in succs:
                c = color.get(m, WHITE)
                if c == GREY:
                    return path[path.index(m):] + [m]
                if c == WHITE:
                    color[m] = GREY
                    path.append(m)
                    stack.append((m, iter(adj.get(m, ()))))
                    advanced = True
                    break
            if not advanced:
                stack.pop()
                path.pop()
                color[node] = BLACK
    return None
