"""Runtime lock-order sanitizer: OrderedLock + a ``threading`` shim.

The static rule (:mod:`mxnet_tpu.analysis.rules.lock_order`) sees what
it can resolve; this is the other half — observe the REAL per-thread
acquisition sequences while the existing CPU test suites (the
dist/fault-injection scenarios especially) run, build the global
lock-order graph, and flag inversions.  The design is a miniature of
TSan's deadlock detector: a lock is identified by its allocation site,
an edge ``A -> B`` means "some thread acquired B while holding A", and
a cycle in the edge set means two threads can deadlock under the right
interleaving even if today's schedule never does.

Two ways in:

* ``OrderedLock(name=...)`` — an explicit instrumented lock for new
  code (drop-in for ``threading.Lock``/``RLock``; works under
  ``threading.Condition`` too, it forwards the ``_release_save`` /
  ``_acquire_restore`` / ``_is_owned`` protocol).
* ``with shim() as graph:`` — monkeypatch ``threading.Lock`` /
  ``threading.RLock`` so every lock CONSTRUCTED inside the block is
  instrumented (existing code unmodified: KVStoreServer, _ServerConn,
  prefetchers...).  After the block, ``graph.assert_acyclic()``.

``strict=True`` raises :class:`LockOrderError` at the acquisition that
would close a cycle — BEFORE blocking on the inner lock, so the
offending test fails instead of deadlocking.  Non-strict records the
violation and lets the run finish (the mode the real fault-injection
suite uses; a recorded graph is asserted acyclic at the end).

Scope/soundness: edges are recorded for blocking acquires only — a
failed or non-blocking ``acquire(False)`` cannot deadlock and would
otherwise flag the benign trylock protocols ``Condition`` uses
internally.  Reentrant re-acquisition (RLock) adds no edge.
"""
from __future__ import annotations

import _thread
import contextlib
import sys
import threading
from typing import Dict, List, Optional, Tuple

from ._graph import find_cycle, reaches


class LockOrderError(RuntimeError):
    """An acquisition closed a cycle in the global lock-order graph."""


def _scheduler():
    """The pluggable yield hook (ISSUE 20): OrderedLock consults the
    controlled scheduler — one shared holder with the hb shim — so a
    lock-order-instrumented lock is also a scheduling point."""
    from . import hb as _hb
    return _hb.scheduler()


def _alloc_site() -> str:
    """file:line of the frame that constructed the lock (first frame
    outside this module and threading.py)."""
    f = sys._getframe(2)
    skip = (__file__, threading.__file__)
    while f is not None and f.f_code.co_filename in skip:
        f = f.f_back
    if f is None:
        return "<unknown>"
    fn = f.f_code.co_filename.rsplit("/", 1)[-1]
    return "%s:%d" % (fn, f.f_lineno)


class LockGraph:
    """Global acquisition-order graph shared by a set of OrderedLocks.

    Thread-safe via a raw ``_thread`` lock so the bookkeeping itself
    can never appear in the graph it maintains."""

    def __init__(self, strict: bool = False):
        self.strict = strict
        self.closed = False
        self._meta = _thread.allocate_lock()
        # (held, acquired) -> (thread name, acquired-at site) 1st witness
        self._edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._adj: Dict[str, set] = {}
        self._held: Dict[int, List[str]] = {}
        self._violations: List[str] = []
        self._acquires = 0

    # -- queries -------------------------------------------------------------
    def edges(self) -> Dict[Tuple[str, str], Tuple[str, str]]:
        with self._meta:
            return dict(self._edges)

    def violations(self) -> List[str]:
        with self._meta:
            return list(self._violations)

    def acquire_count(self) -> int:
        """Total successful acquisitions observed — the liveness probe:
        an edge-free graph is a legitimate result (flat locking), a
        zero acquire count means nothing was instrumented."""
        with self._meta:
            return self._acquires

    def assert_acyclic(self) -> None:
        """Full-graph check (covers violations recorded in non-strict
        mode AND any cycle the incremental check classified late)."""
        with self._meta:
            if self._violations:
                raise LockOrderError(
                    "lock-order violations recorded:\n  " +
                    "\n  ".join(self._violations))
            # incremental insertion flags every cycle as it closes, so
            # a clean violation list implies an acyclic edge set; walk
            # anyway — cheap, and independent of the incremental logic
            cycle = find_cycle(self._adj)
            if cycle is not None:
                raise LockOrderError(
                    "lock-order cycle: %s" % " -> ".join(cycle))

    # -- recording -----------------------------------------------------------
    def _before_acquire(self, name: str, blocking: bool) -> None:
        """Record edges held->name; in strict mode raise on a cycle
        BEFORE the caller blocks on the inner lock."""
        if self.closed or not blocking:
            return
        tid = _thread.get_ident()
        cycle = None
        with self._meta:
            held = self._held.get(tid, ())
            if name in held:
                return   # reentrant (RLock): no new ordering fact
            for h in held:
                if (h, name) in self._edges:
                    continue
                if reaches(self._adj, name, h):
                    cycle = ("thread %r acquiring %s while holding %s "
                             "inverts the established order (%s -> ... "
                             "-> %s exists)" % (
                                 threading.current_thread().name,
                                 name, h, name, h))
                    self._violations.append(cycle)
                self._edges[(h, name)] = (
                    threading.current_thread().name, name)
                self._adj.setdefault(h, set()).add(name)
        if cycle is not None and self.strict:
            raise LockOrderError(cycle)

    def _after_acquire(self, name: str) -> None:
        if self.closed:
            return
        tid = _thread.get_ident()
        with self._meta:
            self._acquires += 1
            self._held.setdefault(tid, []).append(name)

    def _on_release(self, name: str, all_holds: bool = False) -> int:
        if self.closed:
            return 0
        tid = _thread.get_ident()
        n = 0
        with self._meta:
            held = self._held.get(tid, [])
            if all_holds:
                n = held.count(name)
                self._held[tid] = [h for h in held if h != name]
                return n
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    return 1
            # released by a DIFFERENT thread than the acquirer — legal
            # for a plain Lock (the handoff/signal pattern).  Clear the
            # acquirer's entry, or the lock looks held-forever on that
            # thread and every later acquisition there grows a phantom
            # edge (false cycles under the shim).
            for other_held in self._held.values():
                for i in range(len(other_held) - 1, -1, -1):
                    if other_held[i] == name:
                        del other_held[i]
                        return 1
        return n


_DEFAULT_GRAPH = LockGraph(strict=False)


def default_graph() -> LockGraph:
    return _DEFAULT_GRAPH


class OrderedLock:
    """Instrumented lock: records its acquisition order in a
    :class:`LockGraph`.  ``rlock=True`` wraps a reentrant lock.  Locks
    are named by allocation site (all locks born at one line are one
    graph node — the lockset abstraction) unless ``name`` is given."""

    def __init__(self, name: Optional[str] = None,
                 graph: Optional[LockGraph] = None, rlock: bool = False):
        # raw _thread primitives: never affected by the shim
        self._inner = _thread.RLock() if rlock else _thread.allocate_lock()
        self._graph = graph if graph is not None else _DEFAULT_GRAPH
        self._name = name if name is not None else _alloc_site()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._graph._before_acquire(self._name, blocking)
        sch = _scheduler()
        if sch is not None:
            got = sch.lock_acquire(self, blocking, timeout)
            if got is not None:   # modeled: the scheduler owned blocking
                if not got:
                    return False
                self._inner.acquire()
                self._graph._after_acquire(self._name)
                return True
        if timeout == -1:
            ok = self._inner.acquire(blocking)
        else:
            ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._graph._after_acquire(self._name)
        return ok

    def release(self) -> None:
        sch = _scheduler()
        if sch is not None and sch.lock_release(self):
            self._inner.release()
            self._graph._on_release(self._name)
            sch.after_release(self)
            return
        self._inner.release()
        self._graph._on_release(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        if locked is not None:
            return locked()
        # RLock without locked(): owned by anyone iff trylock fails
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    # -- threading.Condition protocol ---------------------------------------
    # Condition(lock) binds these when present; forwarding them keeps
    # cv.wait()'s full-release/re-acquire visible to the graph.
    def _release_save(self):
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        count = self._graph._on_release(self._name, all_holds=True)
        return (state, count)

    def _acquire_restore(self, saved):
        state, count = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        # the waiter held nothing while blocked; re-entering the lock
        # re-records it (same edges as the original acquisition)
        for _ in range(max(1, count)):
            self._graph._after_acquire(self._name)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return "<OrderedLock %s>" % self._name


@contextlib.contextmanager
def shim(strict: bool = False, graph: Optional[LockGraph] = None):
    """Monkeypatch ``threading.Lock``/``threading.RLock`` so every lock
    constructed in the block is an :class:`OrderedLock` recording into
    one :class:`LockGraph` (yielded).  ``threading.Condition()`` with
    no explicit lock picks the patched RLock up automatically.

    Locks outlive the block safely: on exit the graph is closed, so
    escaped instrumented locks keep working but stop recording."""
    g = graph if graph is not None else LockGraph(strict=strict)
    orig_lock, orig_rlock = threading.Lock, threading.RLock

    def make_lock():
        return OrderedLock(name=_alloc_site(), graph=g)

    def make_rlock():
        return OrderedLock(name=_alloc_site(), graph=g, rlock=True)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    try:
        yield g
    finally:
        threading.Lock = orig_lock
        threading.RLock = orig_rlock
        g.closed = True
