"""Controlled concurrency scheduler: PCT exploration + replayable journals.

The happens-before sanitizer (:mod:`mxnet_tpu.analysis.hb`) reports
races that happen to fire under the ONE schedule the OS picked.  This
module makes the schedule an input: it serializes the process to one
runnable thread at a time, choosing who runs next at the yield points
the hb shim already intercepts — lock acquire/release, Condition
wait/notify, ``queue.Queue`` put/get (their mutex and condvars are
born instrumented under the shim), ``Thread`` start/join, ``time.sleep``
and every :func:`hb.track` container access — using PCT-style random
priority scheduling (Burckhardt et al., "A Randomized Scheduler with
Probabilistic Guarantees of Finding Bugs"): each thread gets a random
priority, the highest-priority runnable thread always runs, and
``depth`` − 1 seeded priority-change points demote the running thread
mid-schedule.  ``(seed, scenario)`` therefore names a schedule, and a
failing schedule serializes to an fsync'd JSONL journal that
:func:`replay` re-executes decision for decision.

Mechanics — cooperative baton passing:

* every controlled thread parks on a private raw ``_thread`` gate;
  exactly one holds the TOKEN and executes;
* blocking primitives are MODELED: a lock acquire that would block
  parks the thread in the scheduler (the real inner acquire only ever
  happens after the model granted the lock, so it cannot block);
  Condition waits release/reacquire through the model the same way;
  ``Thread.join`` waits on the model's thread-exit signal; ``sleep``
  and every timed wait park with a real-clock deadline the monitor
  fires — so poll loops keep their real-time semantics;
* a thread that blocks OUTSIDE the model (socket IO, foreign locks)
  is detected by a lease watchdog, marked EXTERNAL, and scheduling
  continues without it; it rejoins at its next yield point.  Pure
  thread scenarios (no sockets, no sleeps) are bit-deterministic;
  socket scenarios are explored best-effort.

On top of the scheduler:

* **deadlock detector** — every live controlled thread blocked on an
  UNTIMED modeled primitive with no external threads outstanding is a
  cycle by construction; the finding names every thread's held and
  waited-for locks with live stacks, then aborts the schedule;
* **starvation budget** — a thread runnable for
  ``MXNET_SCHED_STARVE_OPS`` consecutive decisions without being
  scheduled is a finding (the lost-fairness shape PCT priorities can
  legitimately produce is reset whenever the thread blocks or runs);
* **op budget** — a schedule that makes no progress past
  ``max_ops`` decisions is reported as a livelock and aborted;
* **FastTrack integration** — every schedule runs under a fresh
  :class:`hb.Sanitizer`, so each explored interleaving is also
  race-checked; violations are findings.
"""
from __future__ import annotations

import _thread
import contextlib
import json
import os
import random
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

__all__ = [
    "SchedAbort", "Scheduler", "ScheduleResult", "ExploreResult",
    "run_schedule", "explore", "replay", "read_journal",
]

_mono = time.monotonic
_real_sleep = time.sleep

# How long a replay waits for the journal's expected thread to arrive
# at a yield before declaring the run divergent (module-level so tests
# can tighten it).
_REPLAY_STALL_S = 30.0


class SchedAbort(BaseException):
    """Raised inside controlled threads to unwind an aborted schedule.

    A ``BaseException`` so the bare-thread capture patterns
    (``except Exception``) in scenario code don't swallow the unwind.
    """


# thread states
_NEW, _RUNNABLE, _RUNNING, _BLOCKED, _EXTERNAL, _DONE = "NRGBXD"


class _TS:
    """Per-thread scheduler state."""

    __slots__ = ("thread", "lid", "idx", "tid", "state", "gate",
                 "wake_action", "wake_reason", "wait_kind", "wait_key",
                 "wait_name", "deadline", "prio", "starve",
                 "starve_reported", "held", "external")

    def __init__(self, thread, lid, idx, prio):
        self.thread = thread
        self.lid = lid            # logical id ("T0", "T1", ...) by
        self.idx = idx            # registration order — replay-stable
        self.tid = None           # real ident, filled at thread begin
        self.state = _NEW
        self.gate = _thread.allocate_lock()
        self.gate.acquire()       # parked = gate.acquire() blocks
        self.wake_action = "go"
        self.wake_reason = None
        self.wait_kind = None
        self.wait_key = None
        self.wait_name = None
        self.deadline = None
        self.prio = prio
        self.starve = 0
        self.starve_reported = False
        self.held = []            # _LockModel list, acquisition order
        self.external = False


class _LockModel:
    __slots__ = ("key", "name", "owner", "count", "waiters")

    def __init__(self, key, name):
        self.key = key
        self.name = name
        self.owner = None         # _TS
        self.count = 0
        self.waiters = []         # _TS


class _Journal:
    """Append-only JSONL schedule journal (the autotune-journal
    conventions: one object per line, fsync at the records that must
    survive a crash, torn trailing lines tolerated by the reader)."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._f = open(path, "w") if path else None
        self._n = 0

    def write(self, obj, sync=False) -> None:
        if self._f is None:
            return
        self._f.write(json.dumps(obj) + "\n")
        self._n += 1
        if sync or self._n % 256 == 0:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self, keep: bool) -> None:
        if self._f is None:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        if not keep and self.path:
            try:
                os.unlink(self.path)
            except OSError:
                pass


def _res_name(lock) -> str:
    return (getattr(lock, "name", None)
            or getattr(lock, "_name", None)
            or "lock:%x" % id(lock))


class Scheduler:
    """One schedule's controller.  Installed into the hb/runtime shims
    via :func:`hb.set_scheduler`; every shim interception point calls
    back into it.  All state lives under one raw ``_thread`` meta lock
    so the scheduler can never appear in the graphs it drives."""

    # monitor tick: deadline firing + lease granularity
    _TICK = 0.002

    def __init__(self, seed_key, depth=3, starve_ops=20000,
                 est_ops=256, journal: Optional[_Journal] = None,
                 replay_decisions: Optional[List[str]] = None,
                 lease_s=0.5, max_ops=300000):
        self._meta = _thread.allocate_lock()
        self._rng = random.Random(str(seed_key))
        self._depth = max(1, int(depth))
        self._starve_ops = int(starve_ops)
        self._max_ops = int(max_ops)
        self._lease = float(lease_s)
        self.closed = False
        self.aborting = False
        self._all: List[_TS] = []
        self._suppress: set = set()   # tids temporarily passthrough
        self._by_tid: Dict[int, _TS] = {}
        self._by_thread: Dict[int, _TS] = {}
        self._by_lid: Dict[str, _TS] = {}
        self._token: Optional[_TS] = None
        self._grant_t = 0.0
        self._last: Optional[_TS] = None
        self._di = 0              # decision index
        self._demote = -1.0       # next demotion priority (PCT)
        self._locks: Dict[int, _LockModel] = {}
        self._cvs: Dict[int, List[_TS]] = {}
        self._joiners: Dict[int, List[_TS]] = {}
        self._external_n = 0
        self.findings: List[tuple] = []
        self.decisions: List[tuple] = []   # (lid, op, res) in order
        self._journal = journal or _Journal(None)
        self._replay = replay_decisions
        self._ri = 0
        self._replay_stall_t = None
        # PCT: depth-1 priority change points over the estimated
        # schedule length (the explorer feeds each schedule the
        # previous one's measured length, so the points land inside
        # the actual run)
        hi = max(int(est_ops), self._depth + 1)
        self._change_points = (
            set(self._rng.sample(range(1, hi), self._depth - 1))
            if self._depth > 1 else set())
        self._mon_stop = False

    # -- registration -----------------------------------------------------
    def attach_main(self) -> None:
        """Register the calling thread as T0 and hand it the token."""
        th = threading.current_thread()
        with self._meta:
            ts = self._new_ts_locked(th)
            ts.tid = _thread.get_ident()
            ts.state = _RUNNING
            self._by_tid[ts.tid] = ts
            self._token = ts
            self._grant_t = _mono()
            self._last = ts
        _thread.start_new_thread(self._monitor, ())

    def _new_ts_locked(self, th) -> _TS:
        lid = "T%d" % len(self._all)
        ts = _TS(th, lid, len(self._all), self._rng.random())
        self._all.append(ts)
        self._by_thread[id(th)] = ts
        self._by_lid[lid] = ts
        self._journal.write({"kind": "thread", "lid": lid,
                             "name": th.name})
        return ts

    def thread_spawn(self, th) -> None:
        """Called from the hb shim's patched ``Thread.start`` BEFORE
        the real start — registration order is creation order, which
        is deterministic under the token."""
        if self.closed:
            return
        with self._meta:
            if id(th) not in self._by_thread:
                self._new_ts_locked(th)

    def thread_start(self, th, orig_start) -> None:
        """Deterministic ``Thread.start``: the CPython ``_started``
        Event handshake inside ``orig_start`` races the child's
        uncontrolled bootstrap against the spawner's modeled cv wait —
        whether the flag beats the wait would vary run to run and
        leak into the decision stream.  So the spawner goes
        PASSTHROUGH (real primitives, no decisions journaled) for the
        handshake, then rendezvouses until the child parked at its
        first yield point, then takes one explicit scheduling point:
        every schedule sees the same stream, and PCT gets the classic
        preempt-at-start window."""
        me = self._current()
        if me is None or self.closed:
            orig_start(th)
            return
        tid = _thread.get_ident()
        with self._meta:
            self._suppress.add(tid)
        try:
            orig_start(th)
        finally:
            with self._meta:
                self._suppress.discard(tid)
        ts = self._by_thread.get(id(th))
        if ts is None:
            return
        deadline = _mono() + 10.0
        while _mono() < deadline:
            with self._meta:
                if self.closed or ts.state != _NEW:
                    break
            _real_sleep(0.0002)
        self.yield_point("start", ts.lid)

    def thread_begin(self, th) -> None:
        """First thing a controlled child runs: park until scheduled."""
        ts = self._by_thread.get(id(th))
        if ts is None or self.closed:
            return
        with self._meta:
            ts.tid = _thread.get_ident()
            self._by_tid[ts.tid] = ts
        self._pass_baton(ts, _RUNNABLE, ("begin", ts.lid))

    def thread_end(self, th) -> None:
        ts = self._by_thread.get(id(th))
        if ts is None:
            return
        with self._meta:
            if ts.state == _DONE:
                return
            if ts.external:
                ts.external = False
                self._external_n -= 1
            was_token = self._token is ts
            ts.state = _DONE
            for w in self._joiners.pop(id(th), []):
                if w.state == _BLOCKED and w.wait_kind == "join" \
                        and w.wait_key == id(th):
                    w.state = _RUNNABLE
                    w.wake_reason = "done"
            if self.closed:
                return
            if was_token:
                self._token = None
            if self._token is None:
                chosen = self._pick(("end", ts.lid))
                if chosen is not None:
                    self._dispatch_locked(chosen)
                else:
                    self._check_deadlock_locked()

    def thread_join(self, th, timeout):
        """Modeled join.  Returns 'done', 'timeout', or None
        (uncontrolled caller / unknown thread / closed → real join)."""
        me = self._current()
        if me is None or self.closed:
            return None
        ts = self._by_thread.get(id(th))
        if ts is None:
            return None
        self._pass_baton(me, _RUNNABLE, ("join", ts.lid))
        deadline = _mono() + timeout if timeout is not None else None
        while True:
            with self._meta:
                if self.closed:
                    return None
                if ts.state == _DONE:
                    return "done"
                if deadline is not None and _mono() >= deadline:
                    return "timeout"
                lst = self._joiners.setdefault(id(th), [])
                if me not in lst:
                    lst.append(me)
            r = self._pass_baton(
                me, _BLOCKED, ("wait-join", ts.lid),
                wait=("join", id(th), "join:" + ts.lid, deadline))
            if r == "closed":
                return None

    # -- identity ---------------------------------------------------------
    def _current(self) -> Optional[_TS]:
        tid = _thread.get_ident()
        if tid in self._suppress:
            return None
        ts = self._by_tid.get(tid)
        if ts is None or ts.state == _DONE:
            return None
        return ts

    def is_controlled(self) -> bool:
        return self._current() is not None

    # -- the baton --------------------------------------------------------
    def _pass_baton(self, me, state, op, wait=None) -> str:
        """Move ``me`` to ``state`` (_RUNNABLE or _BLOCKED + wait
        info), pick who runs next, and park until this thread holds
        the token again.  Returns the wake reason; raises
        :class:`SchedAbort` when the schedule is aborting."""
        deadlocked = False
        with self._meta:
            if self.closed:
                return "closed"
            had = self._token is me
            if had:
                self._token = None
            if me.external:
                me.external = False
                self._external_n -= 1
            me.state = state
            me.wake_reason = None
            if state == _BLOCKED:
                me.wait_kind, me.wait_key, me.wait_name, me.deadline = wait
            else:
                me.wait_kind = me.wait_key = me.wait_name = None
                me.deadline = None
                me.starve = 0
            if had or self._token is None:
                chosen = self._pick(op)
                if chosen is me:
                    me.state = _RUNNING
                    self._token = me
                    self._grant_t = _mono()
                    return "go"
                if chosen is not None:
                    self._dispatch_locked(chosen)
                elif state == _BLOCKED:
                    deadlocked = self._check_deadlock_locked()
        if deadlocked:
            raise SchedAbort()
        me.gate.acquire()
        if me.wake_action == "abort":
            raise SchedAbort()
        return me.wake_reason or "go"

    def _dispatch_locked(self, chosen) -> None:
        chosen.state = _RUNNING
        chosen.starve = 0
        chosen.wake_action = "abort" if self.aborting else "go"
        self._token = chosen
        self._grant_t = _mono()
        chosen.gate.release()

    def _pick(self, op) -> Optional[_TS]:
        """Choose the next thread (caller holds meta).  PCT in record
        mode, journal-following in replay mode."""
        runnable = [t for t in self._all if t.state == _RUNNABLE]
        if not runnable:
            return None
        self._di += 1
        if self._di in self._change_points and self._last is not None:
            # PCT priority-change point: demote whoever ran last
            self._last.prio = self._demote
            self._demote -= 1.0
        if self._replay is not None:
            chosen = self._replay_pick_locked(runnable)
            if chosen is None:
                self._di -= 1     # nothing consumed — not a decision
                return None
        else:
            chosen = max(runnable, key=lambda t: (t.prio, -t.idx))
        for t in runnable:
            if t is chosen:
                continue
            t.starve += 1
            if self._starve_ops and t.starve >= self._starve_ops \
                    and not t.starve_reported:
                t.starve_reported = True
                self._finding_locked(
                    "starvation",
                    "%s (%s) stayed runnable for %d consecutive "
                    "scheduling decisions without running (budget "
                    "MXNET_SCHED_STARVE_OPS=%d)"
                    % (t.lid, t.thread.name, t.starve, self._starve_ops))
        self._last = chosen
        res = op[1] if len(op) > 1 else None
        self.decisions.append((chosen.lid, op[0], res))
        self._journal.write({"kind": "d", "i": self._di,
                             "t": chosen.lid, "op": op[0], "r": res})
        if self._di >= self._max_ops and not self.aborting:
            self._finding_locked(
                "op-budget",
                "schedule exceeded %d decisions without finishing — "
                "livelock (or raise max_ops)" % self._max_ops)
            self._abort_locked()
        return chosen

    def _replay_pick_locked(self, runnable) -> Optional[_TS]:
        if self._ri >= len(self._replay):
            # recorded run ended here (abort point); free-run the tail
            return max(runnable, key=lambda t: (t.prio, -t.idx))
        lid = self._replay[self._ri]
        ts = self._by_lid.get(lid)
        if ts is None or ts.state in (_NEW, _EXTERNAL, _RUNNING):
            return None           # not arrived at a yield yet — wait
        if ts.state == _BLOCKED:
            if ts.deadline is not None:
                ts.state = _RUNNABLE     # the recorded timeout firing
                ts.wake_reason = "timeout"
                ts.prio = self._demote   # same demotion as the monitor
                self._demote -= 1.0
            else:
                self._finding_locked(
                    "replay-divergence",
                    "journal expects %s at decision %d but it is "
                    "blocked on %s %s" % (lid, self._ri, ts.wait_kind,
                                          ts.wait_name))
                self._abort_locked()
                return None
        if ts.state != _RUNNABLE:
            return None
        self._ri += 1
        self._replay_stall_t = None
        return ts

    # -- findings / abort -------------------------------------------------
    def _finding_locked(self, kind, detail) -> None:
        self.findings.append((kind, detail))
        self._journal.write({"kind": "finding", "type": kind,
                             "detail": detail}, sync=True)

    def add_finding(self, kind, detail) -> None:
        with self._meta:
            self._finding_locked(kind, detail)

    def _abort_locked(self) -> None:
        """Wake every parked thread with the abort action and go
        passthrough — modeled ops fall back to real primitives so the
        scenario can tear itself down."""
        if self.aborting:
            return
        self.aborting = True
        self.closed = True
        self._mon_stop = True
        me = _thread.get_ident()
        for ts in self._all:
            if ts.state in (_RUNNABLE, _BLOCKED) and ts.tid != me:
                ts.wake_action = "abort"
                ts.state = _RUNNING
                ts.gate.release()
        self._token = None

    def _check_deadlock_locked(self) -> bool:
        """All live controlled threads blocked on UNTIMED modeled
        primitives, none external, none still starting → a wait cycle
        by construction.  Build the who-holds-what report with live
        stacks, record the finding, abort.  Caller holds meta; returns
        True when a deadlock was declared (caller must raise)."""
        if self.closed or self.aborting or self._external_n > 0:
            return False
        live = [t for t in self._all if t.state != _DONE]
        if not live:
            return False
        for t in live:
            if t.state != _BLOCKED or t.deadline is not None:
                return False
        frames = sys._current_frames()
        lines = ["deadlock: all %d live threads blocked on shim "
                 "primitives" % len(live)]
        for t in live:
            held = ", ".join(m.name for m in t.held) or "nothing"
            lines.append(
                "  %s (%s): waiting on %s %s; holding %s"
                % (t.lid, t.thread.name, t.wait_kind, t.wait_name, held))
            f = frames.get(t.tid)
            if f is not None:
                stack = [s for s in traceback.format_stack(f)
                         if "analysis/sched.py" not in s
                         and "analysis/hb.py" not in s]
                lines.append("".join("    " + ln for s in stack[-6:]
                                     for ln in s.splitlines(True)))
        self._finding_locked("deadlock", "\n".join(lines))
        self._abort_locked()
        return True

    # -- yield points -----------------------------------------------------
    def yield_point(self, kind, name) -> None:
        """A pure scheduling point: tracked container accesses, SPSC
        ring probes, notifies."""
        me = self._current()
        if me is None or self.closed:
            return
        self._pass_baton(me, _RUNNABLE, (kind, name))

    def sleep_yield(self, secs) -> bool:
        """Modeled ``time.sleep``: park with a real-clock deadline the
        monitor fires — the sleeper stops holding the token, and poll
        loops keep real-time semantics.  False → caller really sleeps."""
        me = self._current()
        if me is None or self.closed:
            return False
        if secs is None or secs <= 0:
            self._pass_baton(me, _RUNNABLE, ("sleep0", None))
            return True
        r = self._pass_baton(me, _BLOCKED, ("sleep", None),
                             wait=("sleep", None, "sleep(%g)" % secs,
                                   _mono() + secs))
        return r != "closed"

    # -- lock modeling ----------------------------------------------------
    def lock_acquire(self, lock, blocking, timeout):
        """Modeled acquire.  True = granted (the caller's real inner
        acquire is then uncontended), False = nonblocking/timed
        failure, None = uncontrolled caller or closed (caller uses the
        real path)."""
        me = self._current()
        if me is None or self.closed:
            return None
        key = id(lock)
        name = _res_name(lock)
        if timeout is not None and timeout > 0:
            deadline = _mono() + timeout
        else:
            deadline = None
        # the pre-acquire scheduling point: the PCT preemption window
        self._pass_baton(me, _RUNNABLE, ("acquire", name))
        while True:
            with self._meta:
                if self.closed:
                    return None
                m = self._locks.get(key)
                if m is None:
                    m = self._locks[key] = _LockModel(key, name)
                if m.owner is None:
                    m.owner = me
                    m.count = 1
                    me.held.append(m)
                    self._unwait_locked(m, me)
                    return True
                if m.owner is me:
                    m.count += 1
                    return True
                if not blocking:
                    self._unwait_locked(m, me)
                    return False
                if deadline is not None and _mono() >= deadline:
                    self._unwait_locked(m, me)
                    return False
                if me not in m.waiters:
                    m.waiters.append(me)
            r = self._pass_baton(me, _BLOCKED, ("wait-lock", name),
                                 wait=("lock", key, name, deadline))
            if r == "closed":
                return None

    @staticmethod
    def _unwait_locked(m, me) -> None:
        try:
            m.waiters.remove(me)
        except ValueError:
            pass

    def lock_release(self, lock) -> bool:
        """Modeled release bookkeeping (True = modeled; the caller
        performs the real release then calls :meth:`after_release`)."""
        me = self._current()
        if me is None or self.closed:
            return False
        with self._meta:
            m = self._locks.get(id(lock))
            if m is None or m.owner is not me:
                return False      # not modeled-owned → real path
            m.count -= 1
            if m.count > 0:
                return True
            m.owner = None
            try:
                me.held.remove(m)
            except ValueError:
                pass
            self._wake_lock_waiters_locked(m)
        return True

    def _wake_lock_waiters_locked(self, m) -> None:
        for w in m.waiters:
            if w.state == _BLOCKED and w.wait_kind == "lock" \
                    and w.wait_key == m.key:
                w.state = _RUNNABLE
                w.wake_reason = "granted"
        m.waiters = []

    def after_release(self, lock) -> None:
        """The post-release scheduling point (the real lock is free;
        freshly woken waiters are schedulable)."""
        me = self._current()
        if me is None or self.closed:
            return
        self._pass_baton(me, _RUNNABLE, ("release", _res_name(lock)))

    # -- condition modeling ----------------------------------------------
    def cv_wait(self, cv, timeout):
        """Modeled Condition wait: model-release the lock, park on the
        cv, reacquire on wake.  Returns 'notified'/'timeout', or None
        when closed before parking (caller does the real wait)."""
        me = self._current()
        if me is None or self.closed:
            return None
        lock = cv._lock
        key = id(lock)
        name = "cv@" + _res_name(lock)
        saved_count = 0
        with self._meta:
            if self.closed:
                return None
            m = self._locks.get(key)
            if m is not None and m.owner is me:
                saved_count = m.count
                m.count = 0
                m.owner = None
                try:
                    me.held.remove(m)
                except ValueError:
                    pass
                self._wake_lock_waiters_locked(m)
            self._cvs.setdefault(id(cv), []).append(me)
        saved = cv._release_save()      # the real full release
        deadline = _mono() + timeout if timeout is not None else None
        try:
            r = self._pass_baton(me, _BLOCKED, ("wait-cv", name),
                                 wait=("cv", id(cv), name, deadline))
        except SchedAbort:
            self._cv_unwait(cv, me)
            try:
                cv._acquire_restore(saved)
            except Exception:  # noqa: BLE001 — unwinding anyway
                pass
            raise
        self._cv_unwait(cv, me)
        self._lock_reacquire(me, key, name, max(1, saved_count))
        cv._acquire_restore(saved)      # real reacquire — uncontended
        return "notified" if r in ("go", "closed", "granted") else r

    def _cv_unwait(self, cv, me) -> None:
        with self._meta:
            lst = self._cvs.get(id(cv))
            if lst is not None:
                try:
                    lst.remove(me)
                except ValueError:
                    pass

    def _lock_reacquire(self, me, key, name, count) -> None:
        """Blocking modeled reacquire after a cv wait (no timeout: the
        real Condition protocol reacquires unconditionally)."""
        while True:
            with self._meta:
                if self.closed:
                    return
                m = self._locks.get(key)
                if m is None:
                    m = self._locks[key] = _LockModel(key, name)
                if m.owner is None:
                    m.owner = me
                    m.count = count
                    me.held.append(m)
                    self._unwait_locked(m, me)
                    return
                if m.owner is me:
                    m.count += count
                    return
                if me not in m.waiters:
                    m.waiters.append(me)
            r = self._pass_baton(me, _BLOCKED, ("wait-lock", name),
                                 wait=("lock", key, name, None))
            if r == "closed":
                return

    def cv_notify(self, cv, n) -> int:
        """Wake up to ``n`` modeled waiters; returns how many of the
        ``n`` are left for the caller's REAL notify (waiters parked in
        the real cv: uncontrolled threads, post-close stragglers)."""
        if self.closed:
            return n
        woken = 0
        with self._meta:
            lst = self._cvs.get(id(cv))
            while lst and woken < n:
                w = lst.pop(0)
                if w.state == _BLOCKED and w.wait_kind == "cv" \
                        and w.wait_key == id(cv):
                    w.state = _RUNNABLE
                    w.wake_reason = "notified"
                    woken += 1
            if woken and self._token is None and not self.closed:
                chosen = self._pick(("notify-dispatch", None))
                if chosen is not None:
                    self._dispatch_locked(chosen)
        return n - woken

    # -- the monitor ------------------------------------------------------
    def _monitor(self) -> None:
        """Raw background thread: fires real-clock deadlines (timed
        waits, sleeps), leases the token away from threads blocked
        outside the model, and watches replay for stalls."""
        while True:
            _real_sleep(self._TICK)
            with self._meta:
                if self.closed or self._mon_stop:
                    return
                now = _mono()
                for ts in self._all:
                    if ts.state == _BLOCKED and ts.deadline is not None \
                            and now >= ts.deadline:
                        ts.state = _RUNNABLE
                        ts.wake_reason = "timeout"
                        # Timer wakeups go to the BACK of the priority
                        # order: PCT's static priorities assume
                        # bounded-length threads, and a periodic loop
                        # (heartbeat, poller) that kept a high priority
                        # across every firing would starve the threads
                        # doing the actual work forever.
                        ts.prio = self._demote
                        self._demote -= 1.0
                tok = self._token
                if tok is not None and now - self._grant_t > self._lease:
                    # the token holder is blocked outside the model
                    # (socket, foreign lock, long compute): free the
                    # token; the thread rejoins at its next yield
                    tok.state = _EXTERNAL
                    tok.external = True
                    self._external_n += 1
                    self._token = None
                if self._token is None:
                    chosen = self._pick(("monitor", None))
                    if chosen is not None:
                        self._dispatch_locked(chosen)
                    elif self._replay is not None:
                        # replay stall: the expected thread never
                        # arrives (timing-dependent divergence)
                        if self._replay_stall_t is None:
                            self._replay_stall_t = now
                        elif now - self._replay_stall_t > \
                                _REPLAY_STALL_S:
                            self._finding_locked(
                                "replay-divergence",
                                "replay stalled %.0fs waiting for %s "
                                "at decision %d" % (
                                    _REPLAY_STALL_S,
                                    self._replay[self._ri]
                                    if self._ri < len(self._replay)
                                    else "<end>", self._ri))
                            self._abort_locked()

    # -- shutdown ---------------------------------------------------------
    def close(self) -> None:
        """Normal end of schedule: go passthrough, wake every parked
        thread (they resume on real primitives for teardown)."""
        with self._meta:
            if self.closed:
                return
            self.closed = True
            self._mon_stop = True
            me = _thread.get_ident()
            for ts in self._all:
                if ts.state in (_RUNNABLE, _BLOCKED) and ts.tid != me:
                    ts.wake_action = "go"
                    ts.wake_reason = "closed"
                    ts.state = _RUNNING
                    ts.gate.release()
            self._token = None


# -- the Condition / sleep patches -------------------------------------------
class SchedCondition(threading.Condition):
    """Drop-in ``threading.Condition`` whose waits and notifies route
    through the installed scheduler for controlled threads, and behave
    exactly like the stock class otherwise (uncontrolled threads,
    after close).  CPython's ``queue.Queue`` and ``threading.Event``
    look ``threading.Condition`` up at call time, so patching the
    module attribute covers queue put/get blocking and Event waits."""

    def wait(self, timeout=None):
        from . import hb as _hb
        sch = _hb.scheduler()
        if sch is not None and not sch.closed and sch.is_controlled():
            r = sch.cv_wait(self, timeout)
            if r is not None:
                return r != "timeout"
        return super().wait(timeout)

    def notify(self, n=1):
        from . import hb as _hb
        sch = _hb.scheduler()
        if sch is not None and not sch.closed:
            left = sch.cv_notify(self, n)
            if left > 0 and getattr(self, "_waiters", None):
                super().notify(min(left, len(self._waiters)))
            sch.yield_point("notify", "cv@" + _res_name(self._lock))
            return
        super().notify(n)

    def notify_all(self):
        self.notify(1 << 30)

    notifyAll = notify_all


_hook_installed = False


def _ensure_excepthook() -> None:
    """Filter SchedAbort out of ``threading.excepthook`` PERMANENTLY
    (installed at first schedule, idempotent): an aborted controlled
    thread can still be unwinding after ``_patched`` exits, so a
    scoped save/restore races the teardown and leaks tracebacks."""
    global _hook_installed
    if _hook_installed:
        return
    orig_hook = threading.excepthook

    def hook(args):
        # SchedAbort unwinding a controlled thread is the scheduler's
        # own teardown, not a scenario failure — keep stderr clean
        if args.exc_type is not SchedAbort:
            orig_hook(args)

    threading.excepthook = hook
    _hook_installed = True


@contextlib.contextmanager
def _patched(sch):
    """Install the scheduler: hb hook + threading.Condition +
    time.sleep.  Must nest INSIDE ``hb.shim`` so locks are HBLocks."""
    import select as _select_mod
    from . import hb as _hb
    orig_cond = threading.Condition
    orig_sleep = time.sleep
    orig_select = _select_mod.select

    def sched_sleep(secs):
        s = _hb.scheduler()
        if s is not None and s.sleep_yield(secs):
            return
        orig_sleep(secs)

    def sched_select(rlist, wlist, xlist, timeout=None):
        # A TIMED select from a controlled thread is a poll sweep:
        # model the wait as a deadline sleep (so the poller yields the
        # token and gets the timer demotion like any periodic loop)
        # then probe readiness without blocking.  An untimed select is
        # real blocking IO — leave it to the lease watchdog.
        s = _hb.scheduler()
        if (s is not None and timeout is not None
                and s.is_controlled() and not s.closed):
            if timeout > 0:
                s.sleep_yield(timeout)
            else:
                s.yield_point("select", None)
            return orig_select(rlist, wlist, xlist, 0)
        return orig_select(rlist, wlist, xlist, timeout)

    threading.Condition = SchedCondition
    time.sleep = sched_sleep
    _ensure_excepthook()
    _select_mod.select = sched_select
    _hb.set_scheduler(sch)
    try:
        yield
    finally:
        _hb.set_scheduler(None)
        threading.Condition = orig_cond
        time.sleep = orig_sleep
        _select_mod.select = orig_select


@contextlib.contextmanager
def _env_overlay(env: Dict[str, str]):
    saved = {}
    try:
        for k, v in (env or {}).items():
            saved[k] = os.environ.get(k)
            os.environ[k] = str(v)
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# -- schedule results ---------------------------------------------------------
class ScheduleResult:
    def __init__(self, scenario, index, seed, findings, decisions,
                 ops, journal_path, race_count):
        self.scenario = scenario
        self.index = index
        self.seed = seed
        self.findings = findings          # [(kind, detail), ...]
        self.decisions = decisions        # [(lid, op, res), ...]
        self.ops = ops
        self.journal_path = journal_path  # None when clean (deleted)
        self.race_count = race_count

    @property
    def clean(self) -> bool:
        return not self.findings


class ExploreResult:
    def __init__(self, scenario, seed, schedules):
        self.scenario = scenario
        self.seed = seed
        self.schedules: List[ScheduleResult] = schedules

    @property
    def findings(self):
        return [f for r in self.schedules for f in r.findings]

    @property
    def failing(self) -> Optional[ScheduleResult]:
        for r in self.schedules:
            if r.findings:
                return r
        return None


def _default_journal_dir() -> str:
    from ..base import env as _env
    return str(_env("MXNET_SCHED_JOURNAL_DIR", "_sched_journals"))


def run_schedule(scenario, index=0, seed=0, depth=3, starve_ops=None,
                 journal_dir=None, est_ops=256,
                 replay_decisions=None, keep_journal=False,
                 max_ops=300000, lease_s=None) -> ScheduleResult:
    """Run ``scenario`` (a :class:`scenarios.Scenario`) under ONE
    controlled schedule.  The journal is written as the schedule runs
    and kept iff the schedule produced findings (or ``keep_journal``)."""
    from . import hb as _hb
    from ..base import env as _env
    if starve_ops is None:
        starve_ops = int(_env("MXNET_SCHED_STARVE_OPS", 20000))
    if lease_s is None:
        lease_s = getattr(scenario, "lease_s", 0.5)
    journal_dir = journal_dir or _default_journal_dir()
    os.makedirs(journal_dir, exist_ok=True)
    tag = "replay-" if replay_decisions is not None else ""
    path = os.path.join(journal_dir, "%s%s-seed%s-i%d.jsonl"
                        % (tag, scenario.name, seed, index))
    jr = _Journal(path)
    jr.write({"kind": "header", "v": 1, "scenario": scenario.name,
              "seed": seed, "index": index, "depth": depth,
              "starve_ops": starve_ops, "est_ops": est_ops,
              "lease_s": lease_s}, sync=True)
    sch = Scheduler("%s:%s:%s" % (scenario.name, seed, index),
                    depth=depth, starve_ops=starve_ops, est_ops=est_ops,
                    journal=jr, replay_decisions=replay_decisions,
                    max_ops=max_ops, lease_s=lease_s)
    san = _hb.Sanitizer(strict=False)
    with _env_overlay(scenario.env):
        with _hb.shim(san=san):
            with _patched(sch):
                sch.attach_main()
                try:
                    scenario.fn()
                except SchedAbort:
                    pass
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as exc:  # noqa: BLE001 — finding
                    sch.add_finding(
                        "scenario-error",
                        "%s: %s\n%s" % (type(exc).__name__, exc,
                                        traceback.format_exc()))
                finally:
                    sch.close()
    for v in san.violations():
        sch.findings.append(("race", v))
        jr.write({"kind": "finding", "type": "race", "detail": v},
                 sync=True)
    findings = list(sch.findings)
    jr.write({"kind": "end", "decisions": sch._di,
              "findings": len(findings),
              "status": "findings" if findings else "clean"}, sync=True)
    keep = bool(findings) or keep_journal
    jr.close(keep=keep)
    return ScheduleResult(scenario.name, index, seed, findings,
                          list(sch.decisions), sch._di,
                          path if keep else None,
                          len(san.violations()))


def explore(scenario_name, schedules=20, seed=0, depth=None,
            starve_ops=None, journal_dir=None,
            stop_on_finding=True, max_ops=300000) -> ExploreResult:
    """Drive ``scenario_name`` through N seeded schedules.  Each
    schedule feeds the next one's PCT change-point range with its
    measured length, so the priority changes land inside the run."""
    from ..base import env as _env
    from .scenarios import get as _get_scenario
    if depth is None:
        depth = int(_env("MXNET_SCHED_DEPTH", 3))
    sc = _get_scenario(scenario_name)
    est = 256
    results = []
    for i in range(int(schedules)):
        r = run_schedule(sc, index=i, seed=seed, depth=depth,
                         starve_ops=starve_ops, journal_dir=journal_dir,
                         est_ops=est, max_ops=max_ops)
        results.append(r)
        if r.ops > 0:
            est = max(64, r.ops)
        if r.findings and stop_on_finding:
            break
    return ExploreResult(scenario_name, seed, results)


# -- journals -----------------------------------------------------------------
def read_journal(path):
    """Parse a schedule journal: (header, decisions, findings).
    Torn trailing lines (a crash mid-write) are tolerated."""
    header = None
    decisions = []
    findings = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue          # torn line — fsync'd records precede it
            kind = obj.get("kind")
            if kind == "header":
                header = obj
            elif kind == "d":
                decisions.append(obj)
            elif kind == "finding":
                findings.append(obj)
    if header is None:
        raise ValueError("no journal header in %s" % path)
    return header, decisions, findings


def replay(journal_path, journal_dir=None) -> ScheduleResult:
    """Re-execute a recorded schedule decision for decision.  The
    scenario, seed, and depth come from the journal header; the seeded
    RNG re-derives identical priorities, and the pick loop follows the
    journal's thread choices instead of the priorities — so a pure
    thread scenario reproduces bit-identically (same decisions, same
    findings), and a divergence is itself reported as a finding."""
    from .scenarios import get as _get_scenario
    header, decisions, _ = read_journal(journal_path)
    sc = _get_scenario(header["scenario"])
    lids = [d["t"] for d in decisions]
    return run_schedule(
        sc, index=header.get("index", 0), seed=header.get("seed", 0),
        depth=header.get("depth", 3),
        starve_ops=header.get("starve_ops"),
        journal_dir=journal_dir, est_ops=header.get("est_ops", 256),
        replay_decisions=lids, keep_journal=True,
        lease_s=header.get("lease_s"))
