"""Framework-aware static analysis + runtime lock-order sanitizer.

The last several PRs bought their wins by establishing cross-file
invariants that nothing mechanical enforced:

* every host readback in the training hot path routes through the
  ``profiler.record_host_sync`` contract (the sync-free loop, PR 4);
* peer bytes are only ever unpickled through the allowlisted decoder in
  ``kvstore_server`` (PR 3);
* five thread classes (ServerConn IO, heartbeat, prefetch workers,
  server accept loops, async checkpoint writers) follow a lock
  discipline and a sticky-error crash-propagation pattern nobody
  checks.

The reference design centralized all mutation through one dependency
engine so these bugs could not exist (Chen et al., arXiv:1512.01274);
this port is an explicitly concurrent runtime, so — like TensorFlow's
answer (Abadi et al., arXiv:1605.08695) — it ships correctness tooling
instead:

* :mod:`mxnet_tpu.analysis.lint` — an AST linter over the package with
  eight framework-specific rule families (``host-sync``,
  ``unsafe-pickle``, ``lock-order``, ``blocking-under-lock``,
  ``env-knob``, ``bare-thread``, ``protocol-op``, ``raw-send``),
  run as its own CI gate via ``python -m mxnet_tpu.analysis --strict``.
* :mod:`mxnet_tpu.analysis.knobs` — the machine-readable registry view
  of every ``MXNET_*`` environment knob (bridging
  ``base.declare_env``), with the docs-drift check and the generated
  markdown table folded into docs/ROBUSTNESS.md.
* :mod:`mxnet_tpu.analysis.protocol` — the wire-protocol registry
  extracted from the AST (op dispatch chains, ``register_op`` sites,
  client request sites, ``srv.*`` spans) behind the ``protocol-op``
  conformance rule and the generated docs/PROTOCOL.md table
  (``--protocol-table``; ``--check`` fails CI on drift).
* :mod:`mxnet_tpu.analysis.runtime` — an instrumented ``OrderedLock``
  plus a monkeypatchable ``threading`` shim that records per-thread
  lock-acquisition sequences at runtime, builds the global lock-order
  graph and flags inversions — a mini lock-order sanitizer that runs
  on CPU under the existing fault-injection tests.
* :mod:`mxnet_tpu.analysis.hb` — the happens-before RACE sanitizer:
  vector clocks over the same shim (plus queue put/get and thread
  start/join edges) and tracked wrappers for the hot shared
  containers; an unsynchronized write/write or read↔write pair raises
  with both stacks.

Rule catalog, allow-annotation syntax and extension guide:
docs/ANALYSIS.md.
"""
from . import hb  # noqa: F401
from .lint import Finding, run_lint, lint_paths  # noqa: F401
from .runtime import (  # noqa: F401
    LockGraph, LockOrderError, OrderedLock, shim)
