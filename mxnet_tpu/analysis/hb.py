"""Happens-before race sanitizer: vector clocks over the threading shim.

The runtime half of the conformance suite
(:mod:`mxnet_tpu.analysis.runtime` is the lock-ORDER half): observe
the real thread interleavings of the messy scenarios — kill-and-
replay, three-phase handoff, coordinator failover,
``_PullHandle._replan``, mesh fan-in — and flag SHARED-STATE accesses
with no happens-before edge between them.  A data race that today's
schedule happens to serialize is still a bug tomorrow; the
closed-channel hang and the unlocked-bank reads were exactly this
shape.

Design is a miniature of TSan/FastTrack:

* every thread carries a **vector clock**; edges join clocks at
  lock release→acquire (the ``threading.Lock``/``RLock`` shim, with
  the ``Condition`` ``_release_save``/``_acquire_restore`` protocol
  forwarded so cv parks stay visible), ``queue.Queue`` put→get
  (per-item stamping), and ``Thread`` start/join;
* the HOT shared containers (pull cache + push log, dedup windows,
  stats/snapshot banks, the membership ledger banks,
  ``_PullHandle`` entries) are wrapped by :func:`track` — a no-op
  returning the container unchanged unless a sanitizer is ACTIVE
  (``shim()``), so production pays one ``is None`` test per
  construction;
* an access pair with no ordering — write/write or read↔write,
  same container — raises :class:`RaceError` in strict mode AT the
  second access, carrying BOTH stacks; non-strict records it for
  ``assert_race_free()``.

Container checks are deliberately whole-structure: our shared dicts
are one-lock-guarded by design, and Python dict mutation is not
key-independent anyway (iteration vs insert).  Reentrant RLock
re-entry adds no new epoch; thread-ident reuse after a join can only
OVER-order (a missed race, never a false one).

Usage::

    with hb.shim(strict=True) as san:
        ...construct servers/stores and run the scenario...
    san.assert_race_free()
    assert san.op_count() > 0       # proves instrumentation was live
"""
from __future__ import annotations

import _thread
import contextlib
import threading
import traceback
from collections import OrderedDict, deque
from typing import Dict, List, Optional

__all__ = [
    "RaceError", "Sanitizer", "HBLock", "shim", "track", "active",
    "TrackedDict", "TrackedOrderedDict", "TrackedList", "TrackedDeque",
    "set_scheduler", "scheduler", "note_spsc",
]


class RaceError(RuntimeError):
    """Two accesses to tracked state with no happens-before edge."""


_ACTIVE: Optional["Sanitizer"] = None

# The pluggable yield hook (ISSUE 20): when a controlled scheduler is
# installed (analysis.sched), every interception point this shim
# already owns — lock acquire/release, queue put/get (via the patched
# Condition the queue's mutex rides), thread start/join, tracked
# container accesses — doubles as a SCHEDULING point.  None in
# production and under plain hb runs: one global load per op.
_SCHED = None


def set_scheduler(sch) -> None:
    """Install (or clear, with None) the controlled scheduler that the
    shim's yield points report to."""
    global _SCHED
    _SCHED = sch


def scheduler():
    return _SCHED


def active() -> Optional["Sanitizer"]:
    return _ACTIVE


def _stack() -> str:
    """Caller stack, trimmed of sanitizer internals — one half of a
    race report's evidence."""
    frames = traceback.extract_stack()
    keep = [f for f in frames
            if not f.filename.endswith("analysis/hb.py")
            and f.filename != threading.__file__]
    return "".join(traceback.format_list(keep[-8:]))


def _lock_site() -> str:
    """Allocation site of a lock born under the controlled scheduler —
    schedule journals name resources by where they were created."""
    import queue as _queue
    for f in reversed(traceback.extract_stack(limit=12)):
        fn = f.filename
        if fn.endswith("analysis/hb.py") or fn == threading.__file__ \
                or fn == _queue.__file__:
            continue
        return "%s:%d" % (fn.rsplit("/", 1)[-1], f.lineno)
    return "?"


class _Access:
    __slots__ = ("tid", "thread", "epoch", "write", "stack")

    def __init__(self, tid, thread, epoch, write, stack):
        self.tid = tid
        self.thread = thread
        self.epoch = epoch
        self.write = write
        self.stack = stack


class Sanitizer:
    """Vector clocks + the tracked-cell table.  Bookkeeping runs under
    a raw ``_thread`` lock so it can never appear in the graphs it
    checks."""

    def __init__(self, strict: bool = False):
        self.strict = strict
        self.closed = False
        self._meta = _thread.allocate_lock()
        self._clocks: Dict[int, Dict[int, int]] = {}
        self._sync: Dict[object, Dict[int, int]] = {}   # release clocks
        self._cells: Dict[int, Dict[str, object]] = {}  # cid -> cell
        self._owners: Dict[object, tuple] = {}  # SPSC key -> writer
        self._violations: List[str] = []
        self._ops = 0

    # -- clock plumbing (caller holds _meta) ---------------------------------
    def _vc(self, tid) -> Dict[int, int]:
        vc = self._clocks.get(tid)
        if vc is None:
            vc = self._clocks[tid] = {tid: 1}
        return vc

    @staticmethod
    def _join(dst, src) -> None:
        for t, c in src.items():
            if dst.get(t, 0) < c:
                dst[t] = c

    # -- queries -------------------------------------------------------------
    def violations(self) -> List[str]:
        with self._meta:
            return list(self._violations)

    def op_count(self) -> int:
        """Edges + tracked accesses observed — the liveness probe: a
        race-free result with zero ops means nothing was
        instrumented."""
        with self._meta:
            return self._ops

    def assert_race_free(self) -> None:
        with self._meta:
            if self._violations:
                raise RaceError(
                    "unsynchronized accesses recorded:\n" +
                    "\n".join(self._violations))

    # -- happens-before edges ------------------------------------------------
    def acquire_edge(self, key) -> None:
        """this thread ⊒ the last release of ``key``."""
        if self.closed:
            return
        tid = _thread.get_ident()
        with self._meta:
            rel = self._sync.get(key)
            if rel:
                self._join(self._vc(tid), rel)
            self._ops += 1

    def release_edge(self, key) -> None:
        """Publish this thread's clock at ``key``; start a new epoch."""
        if self.closed:
            return
        tid = _thread.get_ident()
        with self._meta:
            vc = self._vc(tid)
            self._sync[key] = dict(vc)
            vc[tid] = vc.get(tid, 1) + 1
            self._ops += 1

    def publish_snapshot(self) -> Dict[int, int]:
        """Clock snapshot + epoch bump — the sending half of a
        point-to-point edge (thread start, queue put)."""
        tid = _thread.get_ident()
        with self._meta:
            vc = self._vc(tid)
            snap = dict(vc)
            vc[tid] = vc.get(tid, 1) + 1
            self._ops += 1
        return snap

    def adopt(self, snap) -> None:
        """The receiving half (thread begin/join, queue get)."""
        if not snap:
            return
        tid = _thread.get_ident()
        with self._meta:
            self._join(self._vc(tid), snap)
            self._ops += 1

    # -- tracked accesses ----------------------------------------------------
    def access(self, cid: int, name: str, write: bool) -> None:
        if self.closed:
            return
        sch = _SCHED
        if sch is not None:
            sch.yield_point("track", name)
        tid = _thread.get_ident()
        me = _Access(tid, threading.current_thread().name, 0, write,
                     _stack())
        new_races = []
        with self._meta:
            vc = self._vc(tid)
            me.epoch = vc.get(tid, 1)
            cell = self._cells.get(cid)
            if cell is None:
                cell = self._cells[cid] = {"write": None, "reads": {}}
            self._ops += 1

            def unordered(prev):
                return prev.tid != tid \
                    and vc.get(prev.tid, 0) < prev.epoch

            w = cell["write"]
            if w is not None and unordered(w):
                new_races.append((w, me))
            if write:
                for r in cell["reads"].values():
                    if unordered(r):
                        new_races.append((r, me))
                cell["write"] = me
                cell["reads"] = {}
            else:
                cell["reads"][tid] = me
            # render while still holding _meta: another thread's race
            # could land in _violations between release and a strict
            # raise, and the error must carry THIS access's stacks
            messages = [
                "RACE on %s: %s by thread %r not ordered against "
                "%s by thread %r\n-- first access stack --\n%s"
                "-- second access stack --\n%s"
                % (name,
                   "write" if prev.write else "read", prev.thread,
                   "write" if cur.write else "read", cur.thread,
                   prev.stack, cur.stack)
                for prev, cur in new_races]
            self._violations.extend(messages)
        if new_races and self.strict:
            raise RaceError(messages[-1])

    def single_writer(self, key, name: str) -> None:
        """Enforce single-WRITER discipline on deliberately lock-free
        state (the shmlane SPSC ring indices): whole-structure vector
        clocks would false-positive there — the rings synchronize
        through the index stores themselves — but the design contract
        is exactly one writer thread per index, and THAT is checkable."""
        if self.closed:
            return
        tid = _thread.get_ident()
        msg = None
        with self._meta:
            self._ops += 1
            have = self._owners.get(key)
            if have is None:
                self._owners[key] = (
                    tid, threading.current_thread().name, _stack())
            elif have[0] != tid:
                msg = ("SPSC single-writer violation on %s: thread %r "
                       "writes an index owned by thread %r\n"
                       "-- owning write stack --\n%s"
                       "-- violating write stack --\n%s"
                       % (name, threading.current_thread().name,
                          have[1], have[2], _stack()))
                self._violations.append(msg)
        if msg is not None and self.strict:
            raise RaceError(msg)


class HBLock:
    """Instrumented lock recording release→acquire edges into a
    :class:`Sanitizer` (drop-in for ``threading.Lock``/``RLock``;
    forwards the ``Condition`` protocol so cv parks re-join the
    notifier's clock on wake)."""

    def __init__(self, san: Sanitizer, rlock: bool = False,
                 name: Optional[str] = None):
        self._inner = _thread.RLock() if rlock else _thread.allocate_lock()
        self._san = san
        self._rlock = rlock
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sch = _SCHED
        if sch is not None:
            got = sch.lock_acquire(self, blocking, timeout)
            if got is not None:      # modeled: the scheduler owns blocking
                if not got:
                    return False
                # granted — uncontended among controlled threads, so the
                # real acquire below is immediate (token serialization
                # keeps the real lock state mirroring the model)
                self._inner.acquire()
                self._san.acquire_edge(id(self))
                return True
        if timeout == -1:
            ok = self._inner.acquire(blocking)
        else:
            ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._san.acquire_edge(id(self))
        return ok

    def release(self) -> None:
        sch = _SCHED
        if sch is not None and sch.lock_release(self):
            self._san.release_edge(id(self))
            self._inner.release()
            sch.after_release(self)   # the post-release scheduling point
            return
        self._san.release_edge(id(self))
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        if locked is not None:
            return locked()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    # -- threading.Condition protocol ---------------------------------------
    def _release_save(self):
        self._san.release_edge(id(self))
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, saved):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(saved)
        else:
            self._inner.acquire()
        self._san.acquire_edge(id(self))

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        if self.name:
            return "<HBLock %s %#x>" % (self.name, id(self))
        return "<HBLock %#x>" % id(self)


# -- tracked containers -------------------------------------------------------
class _TrackedMixin:
    """Shared access hooks; subclasses name their read/write ops."""

    def _hb_init(self, san: Sanitizer, name: str):
        self._hb_san = san
        self._hb_name = name

    def _hb(self, write: bool):
        self._hb_san.access(id(self), self._hb_name, write)


def _reads(*names):
    def deco(cls):
        for n in names:
            def make(n=n):
                base = getattr(cls.__mro__[1], n)

                def read_op(self, *a, **k):
                    self._hb(False)
                    return base(self, *a, **k)
                read_op.__name__ = n
                return read_op
            setattr(cls, n, make())
        return cls
    return deco


def _writes(*names):
    def deco(cls):
        for n in names:
            def make(n=n):
                base = getattr(cls.__mro__[1], n)

                def write_op(self, *a, **k):
                    self._hb(True)
                    return base(self, *a, **k)
                write_op.__name__ = n
                return write_op
            setattr(cls, n, make())
        return cls
    return deco


@_reads("__getitem__", "get", "__contains__", "__iter__", "__len__",
        "keys", "values", "items", "copy")
@_writes("__setitem__", "__delitem__", "pop", "popitem", "clear",
         "update", "setdefault")
class TrackedDict(dict, _TrackedMixin):
    def __init__(self, data, san, name):
        dict.__init__(self, data)
        self._hb_init(san, name)


@_reads("__getitem__", "get", "__contains__", "__iter__", "__len__",
        "keys", "values", "items", "copy")
@_writes("__setitem__", "__delitem__", "pop", "popitem", "clear",
         "update", "setdefault", "move_to_end")
class TrackedOrderedDict(OrderedDict, _TrackedMixin):
    def __init__(self, data, san, name):
        OrderedDict.__init__(self, data)
        self._hb_init(san, name)


@_reads("__getitem__", "__iter__", "__len__", "__contains__", "index",
        "count")
@_writes("__setitem__", "__delitem__", "append", "extend", "insert",
         "pop", "remove", "clear", "sort", "reverse")
class TrackedList(list, _TrackedMixin):
    def __init__(self, data, san, name):
        list.__init__(self, data)
        self._hb_init(san, name)


@_reads("__getitem__", "__iter__", "__len__", "__contains__")
@_writes("append", "appendleft", "extend", "extendleft", "pop",
         "popleft", "remove", "clear")
class TrackedDeque(deque, _TrackedMixin):
    def __init__(self, data, san, name):
        deque.__init__(self, data)
        self._hb_init(san, name)


def track(obj, name: str):
    """Wrap a hot shared container for race checking — identity when
    no sanitizer is active (the production path: one None test per
    CONSTRUCTION, zero per access)."""
    san = _ACTIVE
    if san is None or san.closed:
        return obj
    if isinstance(obj, OrderedDict):
        return TrackedOrderedDict(obj, san, name)
    if isinstance(obj, dict):
        return TrackedDict(obj, san, name)
    if isinstance(obj, list):
        return TrackedList(obj, san, name)
    if isinstance(obj, deque):
        return TrackedDeque(obj, san, name)
    return obj


def note_spsc(key, name: str, write: bool) -> None:
    """Probe for the shmlane rings' free-running indices and dead
    flag: a scheduling point under the controlled scheduler, plus
    single-writer enforcement for index WRITES (the only invariant a
    lock-free SPSC ring actually promises).  The dead flag is a sticky
    monotonic bit both sides may set, so it probes with
    ``write=False``.  No-ops to two global loads in production."""
    sch = _SCHED
    if sch is not None:
        sch.yield_point("spsc", name)
    san = _ACTIVE
    if san is not None and not san.closed and write:
        san.single_writer(key, name)


# -- the shim -----------------------------------------------------------------
class _Stamped:
    """Queue item carrying its producer's clock (put→get edge)."""

    __slots__ = ("item", "san", "snap")

    def __init__(self, item, san, snap):
        self.item = item
        self.san = san
        self.snap = snap


_UNWRAP_INSTALLED = False


def _ensure_unwrap_get():
    """Install the unwrapping ``queue.Queue.get`` ONCE, permanently: a
    queue stamped inside a shim block may still hold ``_Stamped``
    items when the block exits (a _ServerConn drain during teardown),
    and a restored plain ``get`` would hand the wrapper to the
    consumer.  The permanent form costs one isinstance test per get
    and only ever activates after the first shim use."""
    global _UNWRAP_INSTALLED
    if _UNWRAP_INSTALLED:
        return
    import queue as _queue
    orig_get = _queue.Queue.get

    def get(self, *a, **k):
        out = orig_get(self, *a, **k)
        if isinstance(out, _Stamped):
            san = _ACTIVE
            if san is not None and san is out.san:
                san.adopt(out.snap)
            return out.item
        return out

    _queue.Queue.get = get
    _UNWRAP_INSTALLED = True


@contextlib.contextmanager
def shim(strict: bool = False, san: Optional[Sanitizer] = None):
    """Monkeypatch ``threading.Lock``/``RLock`` (every lock constructed
    in the block is an :class:`HBLock` — Conditions and Events pick it
    up automatically), ``queue.Queue.put``/``get`` (per-item clock
    stamping) and ``Thread.start``/``join`` (fork/join edges), and
    activate :func:`track`.  Yields the :class:`Sanitizer`.

    Objects outlive the block safely: on exit the sanitizer closes, so
    escaped locks/containers keep working but stop recording."""
    global _ACTIVE
    import queue as _queue
    s = san if san is not None else Sanitizer(strict=strict)
    prev_active = _ACTIVE
    _ensure_unwrap_get()   # permanent: stamped items outlive the block
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    orig_start, orig_join = threading.Thread.start, threading.Thread.join
    orig_put = _queue.Queue.put

    def make_lock():
        sch = _SCHED
        return HBLock(s, name=_lock_site() if sch is not None else None)

    def make_rlock():
        sch = _SCHED
        return HBLock(s, rlock=True,
                      name=_lock_site() if sch is not None else None)

    def start(self):
        sch = _SCHED if not s.closed else None
        if not s.closed:
            snap = s.publish_snapshot()
            orig_run = self.run
            if sch is not None:
                sch.thread_spawn(self)   # logical id = creation order

            def run():
                s.adopt(snap)
                if sch is not None:
                    sch.thread_begin(self)   # parks until scheduled
                try:
                    orig_run()
                finally:
                    self._hb_final = s.publish_snapshot()
                    if sch is not None:
                        sch.thread_end(self)
            self.run = run
        if sch is not None:
            # deterministic start: the _started handshake runs
            # passthrough, then a rendezvous + one scheduling point
            return sch.thread_start(self, orig_start)
        return orig_start(self)

    def join(self, timeout=None):
        sch = _SCHED
        if sch is not None:
            r = sch.thread_join(self, timeout)
            if r == "timeout":
                # the modeled wait consumed the budget; poke the real
                # join only to sync an already-exited thread state
                orig_join(self, 0.001)
                final = getattr(self, "_hb_final", None)
                if final is not None and not self.is_alive() \
                        and not s.closed:
                    s.adopt(final)
                return
        orig_join(self, timeout)
        final = getattr(self, "_hb_final", None)
        if final is not None and not self.is_alive() and not s.closed:
            s.adopt(final)

    def put(self, item, *a, **k):
        # stamping changes item identity, so only plain Queues (a
        # PriorityQueue's heap must compare raw items)
        if not s.closed and type(self) is _queue.Queue:
            item = _Stamped(item, s, s.publish_snapshot())
        return orig_put(self, item, *a, **k)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    threading.Thread.start = start
    threading.Thread.join = join
    _queue.Queue.put = put
    _ACTIVE = s
    try:
        yield s
    finally:
        threading.Lock = orig_lock
        threading.RLock = orig_rlock
        threading.Thread.start = orig_start
        threading.Thread.join = orig_join
        _queue.Queue.put = orig_put
        _ACTIVE = prev_active
        s.closed = True
