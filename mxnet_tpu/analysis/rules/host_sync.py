"""host-sync: host readbacks in hot-path modules must be deliberate.

The sync-free training loop (docs/PERF_NOTES.md round 8) holds because
every device->host readback in the hot path is one of a handful of
counted, contract-bearing sites: ``NDArray.asnumpy``/``wait_to_read``
record themselves, ``EvalMetric.sync`` and
``module.base_module.chunked_device_get`` record their own tags, and
callbacks are documented as the loop's only sync points.  A new
``.asnumpy()`` / ``jax.device_get`` / ``np.asarray(nd)`` /
``float(nd)`` call site in a hot-path module silently re-grows a
per-batch sync — exactly the regression class the sync-count CI gate
exists for, caught here at the SOURCE line instead of as a count drift.

A site passes when its innermost enclosing function itself calls
``profiler.record_host_sync`` (it IS a counted contract site) or when
it carries an ``# analysis: allow(host-sync): <reason>`` annotation
(typically: the value is already host data, or the site runs once per
epoch/process, not per batch).
"""
from __future__ import annotations

import ast

from ..lint import Finding

# Hot-path modules: package-relative path prefixes (ISSUE 5 list).
_HOT_PREFIXES = ("module/", "gluon/trainer.py", "metric.py",
                 "executor.py", "model.py")

_NUMPY_NAMES = {"numpy"}
_JAX_NAMES = {"jax"}


def _is_hot(ctx) -> bool:
    rel = ctx.relpath.replace("\\", "/")
    return rel.startswith(_HOT_PREFIXES) or ctx.hot_marker


def _import_aliases(tree):
    """module-name -> set of local aliases, for numpy and jax."""
    numpy_alias, jax_alias = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    numpy_alias.add(a.asname or a.name)
                elif a.name == "jax":
                    jax_alias.add(a.asname or a.name)
    return numpy_alias or set(_NUMPY_NAMES), jax_alias or set(_JAX_NAMES)


def _records_host_sync(func_node) -> bool:
    """True when ``func_node``'s OWN body calls record_host_sync —
    nested function defs are not descended into: a closure recording a
    sync does not make its enclosing function a contract site."""
    stack = [func_node]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not func_node:
            continue
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr == "record_host_sync":
                return True
            if isinstance(f, ast.Name) and f.id == "record_host_sync":
                return True
        stack.extend(ast.iter_child_nodes(node))
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, numpy_alias, jax_alias):
        self.numpy_alias = numpy_alias
        self.jax_alias = jax_alias
        self.func_stack = []
        self.hits = []   # (line, message)

    def _in_contract_site(self):
        # INNERMOST function only: one recorded sync must not whitelist
        # every other readback in an enclosing function's whole tree
        return bool(self.func_stack) and \
            _records_host_sync(self.func_stack[-1])

    def visit_FunctionDef(self, node):
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        f = node.func
        hit = None
        if isinstance(f, ast.Attribute):
            if f.attr in ("asnumpy", "wait_to_read"):
                hit = ".%s() is a host-blocking device readback" % f.attr
            elif f.attr == "device_get" and isinstance(f.value, ast.Name) \
                    and f.value.id in self.jax_alias:
                hit = "jax.device_get is a host-blocking device readback"
            elif f.attr == "asarray" and isinstance(f.value, ast.Name) \
                    and f.value.id in self.numpy_alias:
                hit = ("np.asarray forces a device->host copy when its "
                       "argument lives on device")
        elif isinstance(f, ast.Name) and f.id == "float" and node.args \
                and isinstance(node.args[0], ast.Name):
            hit = ("float(x) on a device value is a hidden host sync")
        if hit is not None and not self._in_contract_site():
            self.hits.append((node.lineno, hit))
        self.generic_visit(node)


class _HostSyncRule:
    name = "host-sync"

    def check_file(self, ctx, project):
        if not _is_hot(ctx):
            return
        numpy_alias, jax_alias = _import_aliases(ctx.tree)
        v = _Visitor(numpy_alias, jax_alias)
        v.visit(ctx.tree)
        for line, msg in v.hits:
            yield Finding(
                rule=self.name, path=ctx.relpath, line=line,
                message=msg + " in a hot-path module; route it through "
                "a profiler.record_host_sync contract site (metric.sync"
                ", chunked_device_get, ...) or annotate why it is not a "
                "per-batch sync")


RULE = _HostSyncRule()
