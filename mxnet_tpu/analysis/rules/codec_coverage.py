"""codec-coverage: the binary wire codec's op table mirrors the registry.

mxnet_tpu/wirecodec.py serializes exactly the ops the protocol
registry declares ``codec(binary)`` — its ``HOT_OPS`` literal is
GENERATED (``python -m mxnet_tpu.analysis --codec-table``), never
hand-maintained.  A drifted copy is a correctness hazard in both
directions: a declared-hot op missing from the table silently falls
back to pickle (the perf win evaporates without a test failing), and
a table entry nobody declares means the codec ships frames no handler
is contracted to speak.  This rule keeps the generated block
machine-checked against the extracted registry:

* every op declared ``codec(binary)`` appears in the generated
  ``HOT_OPS`` set (else the table is stale);
* every ``HOT_OPS`` entry is backed by a ``codec(binary)``
  declaration (else the table was hand-edited or the op retired);
* ``CODEC_TABLE_FINGERPRINT`` matches the declared set — hand-edits
  that keep the frozenset parseable still drift-fail;
* declaring ``codec(binary)`` anywhere in scope without a generated
  table module present is itself a finding (the codec is born
  registry-generated).

The byte-level twin is ``--check``'s verbatim-source drift gate; this
rule is the per-op diagnostic that names WHICH op drifted.
"""
from __future__ import annotations

import re

from .. import protocol
from ..lint import Finding

_FP_RE = re.compile(r'^CODEC_TABLE_FINGERPRINT\s*=\s*"([0-9a-f]*)"')
_NAME_RE = re.compile(r'^\s*"([^"]+)",\s*$')


class _CodecCoverageRule:
    name = "codec-coverage"

    def check_file(self, ctx, project):
        project.scratch.setdefault("codec-protocol", []).append(
            protocol.extract_file(ctx))
        for ln, text in enumerate(ctx.lines, start=1):
            if not text.startswith(protocol.CODEC_BEGIN):
                continue
            names, fp, closed = [], None, False
            for off, body in enumerate(ctx.lines[ln:], start=ln + 1):
                if body.startswith(protocol.CODEC_END):
                    closed = True
                    break
                m = _NAME_RE.match(body)
                if m:
                    names.append(m.group(1))
                m = _FP_RE.match(body)
                if m:
                    fp = m.group(1)
            project.scratch.setdefault("codec-modules", []).append(
                (ctx.relpath, ln, names, fp, closed))
            break   # one generated block per module
        return ()

    def finalize(self, project):
        tables = project.scratch.get("codec-protocol", [])
        table = protocol.ProtocolTable()
        for t in tables:
            table.merge(t)
        declared = protocol.codec_ops(table)
        modules = project.scratch.get("codec-modules", [])

        if declared and not modules:
            sites = {(o.path, o.line): o.name for o in table.ops
                     if o.codec == "binary"}
            for (path, line), op in sorted(sites.items()):
                yield Finding(
                    rule=self.name, path=path, line=line,
                    message="op %r is declared codec(binary) but no "
                    "generated codec table exists in scope — generate "
                    "one with `python -m mxnet_tpu.analysis "
                    "--codec-table` (the codec is born "
                    "registry-generated)" % op)
            return

        for path, line, names, fp, closed in modules:
            if not closed:
                yield Finding(
                    rule=self.name, path=path, line=line,
                    message="codec-table:begin has no matching "
                    "codec-table:end — the generated hot-op block is "
                    "truncated; regenerate with `python -m "
                    "mxnet_tpu.analysis --codec-table`")
                continue
            have = set(names)
            for op in declared:
                if op not in have:
                    yield Finding(
                        rule=self.name, path=path, line=line,
                        message="hot op %r is declared codec(binary) "
                        "but missing from the generated HOT_OPS table "
                        "— it silently rides pickle; regenerate with "
                        "`python -m mxnet_tpu.analysis --codec-table`"
                        % op)
            for op in sorted(have - set(declared)):
                yield Finding(
                    rule=self.name, path=path, line=line,
                    message="generated HOT_OPS entry %r has no "
                    "codec(binary) declaration in the registry — "
                    "hand-edited or retired; regenerate with "
                    "`python -m mxnet_tpu.analysis --codec-table`"
                    % op)
            want_fp = protocol.codec_fingerprint(declared)
            if fp != want_fp:
                yield Finding(
                    rule=self.name, path=path, line=line,
                    message="CODEC_TABLE_FINGERPRINT %r does not match "
                    "the declared codec(binary) op set (want %r) — "
                    "regenerate with `python -m mxnet_tpu.analysis "
                    "--codec-table`" % (fp, want_fp))


RULE = _CodecCoverageRule()
