"""raw-send: client traffic rides the exactly-once envelope machinery.

``_send_msg`` / ``_recv_msg`` are the FRAME layer.  Everything a
client says to a server must travel as ``("req", (rank, nonce), seq,
msg)`` through ``_ServerConn`` — that envelope is what buys reconnect
+ full-window replay + server-side dedup (exactly-once), tracing
propagation, fault-injection targeting and the byte counters.  A raw
``_send_msg`` call outside the transport layer silently opts out of
every one of those: its message is lost on the first transport fault
and replays are re-applied, the lost-gradient shape PR 13's gate run
caught.

Allowlisted transport internals (the machinery itself):

* ``kvstore_server.py`` — defines the frame fns; the server side,
  one-shot relay/sweep dials and the beat loop speak raw by design
  (beats/heartbeats must never stall behind a delay-acks fault plan).
* ``kvstore._ServerConn`` — the envelope machinery.
* ``kvstore._MeshLeader`` — the in-host fan-in endpoint's serve half.
* ``serving/replica.py`` — the replica's pipelined serve/reply half.

Anything else — a new subsystem dialing a server directly — is a
finding; route through ``_ServerConn.request``/``submit`` or annotate
with the reason the raw channel is exempt from the replay contract
(heartbeat-class liveness traffic is the usual one).
"""
from __future__ import annotations

import ast

from ..lint import Finding

_FRAME_FNS = ("_send_msg", "_recv_msg")

# (module-relpath predicate, class name or None=whole module)
_ALLOWED = (
    ("kvstore_server.py", None),
    ("kvstore.py", "_ServerConn"),
    ("kvstore.py", "_MeshLeader"),
    ("serving/replica.py", None),
)


def _allowed(relpath: str, cls) -> bool:
    rel = relpath.replace("\\", "/")
    for mod, klass in _ALLOWED:
        # anchor on a path segment: tools_kvstore_server.py must NOT
        # inherit kvstore_server.py's exemption
        if (rel == mod or rel.endswith("/" + mod)) \
                and (klass is None or klass == cls):
            return True
    return False


class _RawSendRule:
    name = "raw-send"

    def check_file(self, ctx, project):
        stack = []

        def walk(node):
            is_cls = isinstance(node, ast.ClassDef)
            if is_cls:
                stack.append(node.name)
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            if is_cls:
                stack.pop()

        def visit(node):
            if isinstance(node, ast.Call):
                f = node.func
                name = None
                if isinstance(f, ast.Name) and f.id in _FRAME_FNS:
                    name = f.id
                elif isinstance(f, ast.Attribute) \
                        and f.attr in _FRAME_FNS:
                    name = f.attr
                cls = stack[-1] if stack else None
                if name is not None and not _allowed(ctx.relpath, cls):
                    yield Finding(
                        rule=self.name, path=ctx.relpath,
                        line=node.lineno,
                        message="raw %s outside the transport layer: "
                        "client traffic must ride the ('req', (rank, "
                        "nonce), seq, msg) envelope (_ServerConn."
                        "request/submit) to get reconnect+replay+"
                        "dedup; annotate if this is heartbeat-class "
                        "liveness traffic" % name)
            yield from walk(node)

        yield from walk(ctx.tree)


RULE = _RawSendRule()
