"""lock-order: the static lock-acquisition graph must stay acyclic.

The concurrent transport (ServerConn IO threads, heartbeat threads,
prefetch workers, server accept loops) works because locks are always
taken in one global order.  This rule extracts the static acquisition
graph — ``with lock:`` nesting and ``.acquire()`` calls, one hop of
intra-package call-following — and fails on cycles: two code paths that
take the same pair of locks in opposite orders can deadlock under the
right thread interleaving even if every test passes today.

Lock identity is the canonical attribute path (``module.Class._lock``,
``module._lock``): all instances sharing an allocation site are one
node, the standard abstraction for order analysis.  An expression
counts as a lock when its last component looks like one
(``*_lock`` / ``*_cv`` / ``*_cond`` / ``lock`` / ``mutex``).

Call-following is intentionally shallow (names resolved inside the
package only) — the runtime sanitizer in
:mod:`mxnet_tpu.analysis.runtime` covers what static resolution cannot
see.  A cyclic edge that is provably benign (e.g. guarded by a
try-order protocol) carries ``# analysis: allow(lock-order): <reason>``
at the acquisition or call site.
"""
from __future__ import annotations

import ast
import re

from .._graph import reaches
from ..lint import Finding

_LOCKISH = re.compile(r"(^|_)(lock|locks|mutex|cv|cond|condition)$",
                      re.IGNORECASE)


def _expr_path(node):
    """Dotted text of a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _lock_name(node, mod, cls):
    parts = _expr_path(node)
    if not parts or not _LOCKISH.search(parts[-1]):
        return None
    if parts[0] in ("self", "cls"):
        scope = "%s.%s" % (mod, cls) if cls else mod
        return "%s.%s" % (scope, ".".join(parts[1:]))
    return "%s.%s" % (mod, ".".join(parts))


class _FuncRecord:
    def __init__(self, fid):
        self.fid = fid
        # (lock, line, held-tuple) at each direct acquisition
        self.acquisitions = []
        # (callee-candidate-tuple, held-tuple, line)
        self.calls = []
        # (desc, line, held-tuple, waited-lock-or-None) at each call
        # that can block (socket IO, cv/event waits, wire rounds) —
        # consumed by the blocking-under-lock rule, which shares this
        # extractor so both rules see one acquisition graph
        self.blocking = []


# Calls that can park the thread: holding a lock across one stalls
# every sibling of that lock (and a cv-less wait can deadlock).  A
# ``.wait``/``.wait_for`` whose receiver IS a held condition is the
# legitimate cv-park pattern (wait releases the lock) — recorded with
# its receiver so the rule can exempt it, while CALLERS of the parking
# function under a DIFFERENT lock still get flagged transitively.
_BLOCKING_ATTRS = frozenset({
    "sendall", "recv", "recv_into", "accept", "connect",
    "create_connection", "wait", "wait_for", "select", "sleep",
    "device_get", "mesh_collect", "collect_push", "barrier",
    "_oneshot_request", "submit",
})
_BLOCKING_NAMES = frozenset({"_send_msg", "_recv_msg", "_await"})


def resolve_callee(table, cands):
    """Resolve a call's candidate ids against the extracted function
    table (exact id, or suffix match for module-qualified ``*.mod.fn``
    candidates).  Shared by this rule's cycle closure and the
    blocking-under-lock rule — one resolution scheme, never two."""
    for c in cands:
        if c.startswith("*."):
            suffix = c[1:]          # ".mod.func"
            for fid in table:
                if fid.endswith(suffix):
                    return fid
        elif c in table:
            return c
    return None


class _Extractor:
    """Walk one file, recording per-function acquisitions and calls
    with the held-lock set live at each point."""

    def __init__(self, ctx, mod):
        self.ctx = ctx
        self.mod = mod
        self.cls = None
        self.func = None       # current _FuncRecord
        self.held = []
        self.records = {}

    def run(self):
        self._walk(self.ctx.tree)
        return self.records

    def _walk(self, node):
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _visit(self, node):
        if isinstance(node, ast.ClassDef):
            prev, self.cls = self.cls, node.name
            self._walk(node)
            self.cls = prev
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = "%s.%s" % (self.mod, self.cls) if self.cls else self.mod
            fid = "%s.%s" % (scope, node.name)
            prev_f, prev_h = self.func, self.held
            self.func = self.records.setdefault(fid, _FuncRecord(fid))
            self.held = []
            self._walk(node)
            self.func, self.held = prev_f, prev_h
        elif isinstance(node, ast.With):
            pushed = 0
            for item in node.items:
                lock = _lock_name(item.context_expr, self.mod, self.cls)
                if lock is not None:
                    self._acquire(lock, item.context_expr.lineno)
                    self.held.append(lock)
                    pushed += 1
                else:
                    self._visit(item.context_expr)
            for stmt in node.body:
                self._visit(stmt)
            for _ in range(pushed):
                self.held.pop()
        elif isinstance(node, ast.Call):
            self._call(node)
            self._walk(node)
        else:
            self._walk(node)

    def _acquire(self, lock, line):
        if self.func is not None:
            self.func.acquisitions.append((lock, line, tuple(self.held)))

    def _call(self, node):
        f = node.func
        # explicit .acquire() on a lock expression
        if isinstance(f, ast.Attribute) and f.attr == "acquire":
            lock = _lock_name(f.value, self.mod, self.cls)
            if lock is not None:
                self._acquire(lock, node.lineno)
                return
        if self.func is None:
            return
        blocking = self._blocking_desc(node)
        if blocking is not None:
            self.func.blocking.append(
                (blocking[0], node.lineno, tuple(self.held),
                 blocking[1]))
        cands = None
        if isinstance(f, ast.Name):
            scope = "%s.%s" % (self.mod, self.cls) if self.cls else None
            cands = ("%s.%s" % (self.mod, f.id),) + (
                ("%s.%s" % (scope, f.id),) if scope else ())
        elif isinstance(f, ast.Attribute):
            parts = _expr_path(f)
            if parts and parts[0] in ("self", "cls") and len(parts) == 2 \
                    and self.cls:
                cands = ("%s.%s.%s" % (self.mod, self.cls, parts[1]),)
            elif parts and len(parts) == 2:
                # module-qualified call: matched by suffix at finalize
                cands = ("*.%s.%s" % (parts[0], parts[1]),)
        if cands:
            self.func.calls.append((cands, tuple(self.held), node.lineno))

    def _blocking_desc(self, node):
        """(description, waited-lock-or-None) when the call can block,
        else None (see _BLOCKING_ATTRS)."""
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in _BLOCKING_NAMES:
                return f.id, None
            return None
        if not (isinstance(f, ast.Attribute)
                and f.attr in _BLOCKING_ATTRS):
            return None
        if f.attr == "submit":
            # only the awaited form blocks: submit(..., wait=True)
            if not any(kw.arg == "wait"
                       and isinstance(kw.value, ast.Constant)
                       and kw.value.value for kw in node.keywords):
                return None
        waited = None
        if f.attr in ("wait", "wait_for"):
            waited = _lock_name(f.value, self.mod, self.cls)
        return "." + f.attr, waited


class _LockOrderRule:
    name = "lock-order"

    def check_file(self, ctx, project):
        mod = ctx.relpath.replace("\\", "/")
        mod = re.sub(r"\.py$", "", mod).replace("/", ".")
        mod = re.sub(r"\.__init__$", "", mod)
        records = _Extractor(ctx, mod).run()
        table = project.scratch.setdefault("lock-order", {})
        for fid, rec in records.items():
            table.setdefault(fid, rec)
            project.scratch.setdefault("lock-order-files", {})[fid] = \
                ctx.relpath
        return ()

    def finalize(self, project):
        table = project.scratch.get("lock-order", {})
        files = project.scratch.get("lock-order-files", {})
        if not table:
            return

        def resolve(cands):
            return resolve_callee(table, cands)

        # transitive closure of locks each function acquires
        closure = {fid: {a[0] for a in rec.acquisitions}
                   for fid, rec in table.items()}
        changed = True
        while changed:
            changed = False
            for fid, rec in table.items():
                for cands, _held, _line in rec.calls:
                    callee = resolve(cands)
                    if callee is None:
                        continue
                    extra = closure[callee] - closure[fid]
                    if extra:
                        closure[fid] |= extra
                        changed = True

        # edge set: (a, b) -> list of (file, line, via)
        edges = {}

        def add_edge(a, b, path, line, via):
            if a == b:
                return   # reentrant re-acquisition (RLock pattern)
            edges.setdefault((a, b), []).append((path, line, via))

        for fid, rec in table.items():
            path = files.get(fid, "?")
            for lock, line, held in rec.acquisitions:
                for h in held:
                    add_edge(h, lock, path, line, "direct")
            for cands, held, line in rec.calls:
                callee = resolve(cands)
                if callee is None or not held:
                    continue
                for lock in closure[callee]:
                    for h in held:
                        add_edge(h, lock, path, line,
                                 "via call to %s" % callee)

        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        for (a, b), sites in sorted(edges.items()):
            if not reaches(adj, b, a):
                continue
            for path, line, via in sites:
                yield Finding(
                    rule=self.name, path=path, line=line,
                    message="acquiring %s while holding %s (%s) closes "
                    "a lock-order cycle — another path takes these "
                    "locks in the opposite order; pick one global "
                    "order or annotate why the interleaving is "
                    "impossible" % (b, a, via))


RULE = _LockOrderRule()
