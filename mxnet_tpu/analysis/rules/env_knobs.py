"""env-knob: every MXNET_* getenv is declared, live, and documented.

``base.declare_env`` is the machine-readable knob registry
(:mod:`mxnet_tpu.analysis.knobs` is its analysis-facing view).  Knob
rot has two directions and this rule closes both:

* **undeclared read** — a ``MXNET_*`` name consulted via
  ``base.env`` / ``os.environ.get`` / ``os.getenv`` / subscript that
  was never ``declare_env``-ed: invisible to ``list_env_flags()``, to
  the generated ROBUSTNESS.md knob table, and to anyone tuning a job.
* **stale declaration** (package mode only) — a registered knob no
  code reads: documentation describing behavior that no longer exists.

Package mode also checks the docs themselves: every registered knob
must appear in docs/ROBUSTNESS.md (regenerate the folded table with
``python -m mxnet_tpu.analysis --knob-table``).
"""
from __future__ import annotations

import ast

from ..lint import Finding

_ENV_OBJS = {"environ"}


def _is_env_func(name: str) -> bool:
    """Call names that perform an env lookup: ``env``/``getenv`` and
    local aliases like ``_env`` / ``_base_env`` — but never
    ``declare_env``, which is the registration itself."""
    if name == "declare_env":
        return False
    return name in ("env", "getenv") or name.endswith("_env")


def _mxnet_literal(node):
    # BENCH_* counts too: the bench-script knobs are registered (that is
    # what makes them autotune-able), so a package-internal read of an
    # undeclared BENCH_ name is the same rot as an undeclared MXNET_ one
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith(("MXNET_", "BENCH_")):
        return node.value
    return None


def _read_site(node):
    """Knob name if ``node`` is an env-lookup call/subscript."""
    if isinstance(node, ast.Call):
        f = node.func
        name = None
        if isinstance(f, ast.Name) and _is_env_func(f.id):
            name = True
        elif isinstance(f, ast.Attribute):
            if f.attr in ("get", "pop", "setdefault") \
                    and _is_environ(f.value):
                name = True
            elif _is_env_func(f.attr):
                # module-qualified reads: base.env(...), os.getenv(...)
                name = True
        if name and node.args:
            return _mxnet_literal(node.args[0])
    elif isinstance(node, ast.Subscript) and _is_environ(node.value):
        sl = node.slice
        return _mxnet_literal(sl)
    return None


def _is_environ(node):
    if isinstance(node, ast.Name) and node.id in _ENV_OBJS:
        return True
    if isinstance(node, ast.Attribute) and node.attr in _ENV_OBJS:
        return True
    return False


def _registry():
    from ..knobs import registry
    return registry()


class _EnvKnobRule:
    name = "env-knob"

    def check_file(self, ctx, project):
        reads = project.scratch.setdefault("env-knob-reads", set())
        declared = _registry()
        for node in ast.walk(ctx.tree):
            # declare_env("MXNET_X", ...) is the registration itself
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "declare_env":
                continue
            knob = _read_site(node)
            if knob is None:
                continue
            reads.add(knob)
            if knob not in declared:
                yield Finding(
                    rule=self.name, path=ctx.relpath, line=node.lineno,
                    message="env knob %s is read here but never "
                    "declared via base.declare_env — invisible to "
                    "list_env_flags(), the ROBUSTNESS.md knob table "
                    "and the --knob-table export; declare it with a "
                    "type, default and doc string" % knob)

    def finalize(self, project):
        if not project.is_package:
            return
        from ..knobs import docs_missing, registry
        reads = project.scratch.get("env-knob-reads", set())
        base_ctx = next((c for c in project.files
                         if c.relpath == "base.py"), None)

        def _decl_line(knob):
            if base_ctx is not None:
                for ln, text in enumerate(base_ctx.lines, start=1):
                    if '"%s"' % knob in text:
                        return ln
            return 1

        reg = registry()
        for knob in sorted(set(reg) - reads):
            if not knob.startswith("MXNET_"):
                # BENCH_* rows are the bench-script surface: read by
                # bench.py / benchmark/* at the repo root, OUTSIDE the
                # linted package — registered so autotune can derive
                # their axes, not because package code consults them
                continue
            yield Finding(
                rule=self.name, path="base.py", line=_decl_line(knob),
                message="env knob %s is declared in the registry but "
                "no code reads it — stale documentation; wire it up "
                "or delete the declaration" % knob)
        # tunable-but-undeclared: every axis a built-in autotune target
        # sweeps must resolve to a registered knob — the space builder
        # raises at runtime, this catches the drift before any sweep
        from ...autotune.targets import all_target_knobs
        for target, names in sorted(all_target_knobs().items()):
            for knob in names:
                if knob not in reg:
                    yield Finding(
                        rule=self.name, path="autotune/targets.py",
                        line=1,
                        message="autotune target %r sweeps knob %s "
                        "which is not declared via base.declare_env — "
                        "undeclared knobs can never be tuned; declare "
                        "it (with tune= metadata) or drop the axis"
                        % (target, knob))
        for knob, entry in sorted(reg.items()):
            if not entry.doc:
                yield Finding(
                    rule=self.name, path="base.py",
                    line=_decl_line(knob),
                    message="env knob %s is declared with an EMPTY doc "
                    "string — the generated ROBUSTNESS.md table would "
                    "ship a blank 'what it does' row; say what it "
                    "does" % knob)
        missing, docs_path = docs_missing(project.root)
        for knob in missing:
            yield Finding(
                rule=self.name, path=str(docs_path), line=1,
                message="env knob %s is registered but absent from the "
                "ROBUSTNESS.md knob table; regenerate it with "
                "`python -m mxnet_tpu.analysis --knob-table`" % knob)


RULE = _EnvKnobRule()
