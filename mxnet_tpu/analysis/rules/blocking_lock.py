"""blocking-under-lock: no blocking call while holding a lock.

The stranded ``_io_loop`` waiters PR 5 found and the promotion-sweep
stall this codebase already engineered around (``_promote_to_
coordinator`` deliberately sweeps peers BEFORE taking the ledger
lock) are one hazard: a thread parks on the network / a condition /
a wire round while holding a lock, and every sibling of that lock
wedges behind it — under the right interleaving, forever.

This rule computes it statically from the SAME acquisition graph the
lock-order rule extracts (``with lock:`` nesting, ``.acquire()``
calls, one hop of intra-package call-following): a call that can
block — socket send/recv/accept/connect, ``.wait()``/``.wait_for()``,
``select``, ``sleep``, ``device_get``, the wire helpers
(``_send_msg``/``_recv_msg``/``_await``/``_oneshot_request``/
``submit(..., wait=True)``), barrier parks, mesh fan-in
(``collect_push``/``mesh_collect``) — made while a lock is held is a
finding, directly or through a resolvable callee.

The one legal shape is the condition-variable park: ``cv.wait()``
while holding ``cv`` RELEASES the lock before parking, so a wait
whose receiver is exactly the held lock is exempt — but a caller
parking that cv while holding a DIFFERENT lock is still flagged.
A deliberate block-under-lock (a handle lock whose very contract is
serializing waiters) carries
``# analysis: allow(blocking-under-lock): <reason>``.
"""
from __future__ import annotations

from ..lint import Finding
from .lock_order import resolve_callee


class _BlockingLockRule:
    name = "blocking-under-lock"

    # no check_file: the lock-order rule (registered earlier in
    # ALL_RULES) populates project.scratch["lock-order"] with the
    # shared per-function records, including blocking sites.

    def check_file(self, ctx, project):
        return ()

    def finalize(self, project):
        table = project.scratch.get("lock-order", {})
        files = project.scratch.get("lock-order-files", {})
        if not table:
            return

        def resolve_all(cands):
            """Like the lock-order resolve, plus a subclass fallback:
            a self-call that misses exactly (``_WireHandle.wait``
            calling ``self._resolve``, defined only on subclasses)
            unions every same-module method of that name — blocking is
            a may-property, so over-approximating candidates is the
            sound direction."""
            exact = resolve_callee(table, cands)
            if exact is not None:
                return [exact]
            for c in cands:
                if c.startswith("*."):
                    continue
                head, _, meth = c.rpartition(".")
                mod = head.rpartition(".")[0]
                if not mod:
                    continue
                hits = [fid for fid in table
                        if fid.startswith(mod + ".")
                        and fid.endswith("." + meth)
                        and fid.count(".") > mod.count(".") + 1]
                if hits:
                    return hits
            return []

        # closure of (desc, waited) blocking facts per function
        closure = {fid: {(d, w) for d, _l, _h, w in rec.blocking}
                   for fid, rec in table.items()}
        changed = True
        while changed:
            changed = False
            for fid, rec in table.items():
                for cands, _held, _line in rec.calls:
                    for callee in resolve_all(cands):
                        extra = closure[callee] - closure[fid]
                        if extra:
                            closure[fid] |= extra
                            changed = True

        def offending(entries, held):
            """Blocking facts not excused by the cv-park pattern for
            this held set."""
            return [(d, w) for d, w in entries
                    if not (w is not None and w in held)]

        for fid, rec in sorted(table.items()):
            path = files.get(fid, "?")
            for desc, line, held, waited in rec.blocking:
                if not held:
                    continue
                if waited is not None and waited in held:
                    continue   # cv park: wait releases the held lock
                yield Finding(
                    rule=self.name, path=path, line=line,
                    message="blocking call %s while holding %s — "
                    "every sibling of the lock wedges behind this "
                    "park; move the blocking call outside the "
                    "critical section or annotate why the stall is "
                    "bounded" % (desc, ", ".join(held)))
            for cands, held, line in rec.calls:
                if not held:
                    continue
                callees = [c for c in resolve_all(cands) if c != fid]
                bad = offending(
                    {b for c in callees for b in closure[c]}, held)
                if bad:
                    descs = ", ".join(sorted({d for d, _ in bad}))
                    yield Finding(
                        rule=self.name, path=path, line=line,
                        message="call to %s while holding %s can "
                        "block (%s) — the lock is held across a "
                        "park; hoist the call or annotate why the "
                        "stall is bounded"
                        % (" | ".join(sorted(callees)),
                           ", ".join(held), descs))


RULE = _BlockingLockRule()
