"""Rule registry: one module per rule family (docs/ANALYSIS.md).

Adding a rule = add a module exposing a ``RULE`` object with a ``name``
string, a ``check_file(ctx, project)`` generator, and optionally a
``finalize(project)`` generator for whole-package facts, then list it
here and give it a fixture pair under tests/analysis_fixtures/.
"""
from . import bare_thread, env_knobs, host_sync, lock_order, unsafe_pickle

ALL_RULES = (
    host_sync.RULE,
    unsafe_pickle.RULE,
    lock_order.RULE,
    env_knobs.RULE,
    bare_thread.RULE,
)

RULE_NAMES = tuple(r.name for r in ALL_RULES)
