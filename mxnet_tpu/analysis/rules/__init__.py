"""Rule registry: one module per rule family (docs/ANALYSIS.md).

Adding a rule = add a module exposing a ``RULE`` object with a ``name``
string, a ``check_file(ctx, project)`` generator, and optionally a
``finalize(project)`` generator for whole-package facts, then list it
here and give it a fixture pair under tests/analysis_fixtures/.
"""
from . import (bare_thread, blocking_lock, codec_coverage, env_knobs,
               host_sync, lock_order, protocol_ops, raw_send,
               unsafe_pickle)

ALL_RULES = (
    host_sync.RULE,
    unsafe_pickle.RULE,
    lock_order.RULE,
    # blocking-under-lock consumes the acquisition records lock-order's
    # check_file accumulates — keep it AFTER lock_order here
    blocking_lock.RULE,
    env_knobs.RULE,
    bare_thread.RULE,
    protocol_ops.RULE,
    raw_send.RULE,
    codec_coverage.RULE,
)

RULE_NAMES = tuple(r.name for r in ALL_RULES)
