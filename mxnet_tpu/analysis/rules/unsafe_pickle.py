"""unsafe-pickle: peer bytes decode ONLY through the allowlisted path.

``kvstore_server._recv_msg`` decodes bytes from any connected peer; a
stock ``pickle.loads`` on that surface is arbitrary code execution
(PR 3 landed the class-allowlisted ``_RestrictedUnpickler`` and pinned
hostile-payload tests).  This rule flags every ``pickle.loads`` /
``pickle.load`` / ``pickle.Unpickler`` reference in the package so no
new decode site can bypass the allowlist silently.  ``pickle.dumps``
(encoding) is fine.

Legitimate exceptions — the restricted decoder itself, and loads of
TRUSTED LOCAL files (a checkpoint this process wrote) — carry
``# analysis: allow(unsafe-pickle): <reason>`` annotations; the reason
must say why the bytes cannot be peer-controlled.
"""
from __future__ import annotations

import ast

from ..lint import Finding

_BAD_ATTRS = ("loads", "load", "Unpickler")


class _UnsafePickleRule:
    name = "unsafe-pickle"

    def check_file(self, ctx, project):
        pickle_aliases = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "pickle":
                        pickle_aliases.add(a.asname or "pickle")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "pickle":
                    for a in node.names:
                        if a.name in _BAD_ATTRS:
                            yield Finding(
                                rule=self.name, path=ctx.relpath,
                                line=node.lineno,
                                message="direct import of pickle.%s; "
                                "peer bytes must go through the "
                                "kvstore_server allowlisted decoder "
                                "(_RestrictedUnpickler / "
                                "loads_allowlisted)" % a.name)
        if not pickle_aliases:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in pickle_aliases \
                    and node.attr in _BAD_ATTRS:
                yield Finding(
                    rule=self.name, path=ctx.relpath, line=node.lineno,
                    message="pickle.%s can execute attacker-chosen code "
                    "on peer-controlled bytes; decode through the "
                    "kvstore_server allowlist (_restricted_loads) or "
                    "annotate why these bytes are trusted-local"
                    % node.attr)


RULE = _UnsafePickleRule()
