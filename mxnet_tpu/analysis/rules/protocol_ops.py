"""protocol-op: every wire op is declared replay-safe; no stray ops.

The exactly-once envelope replays the whole unacked window on every
reconnect, so replay-safety is a CORRECTNESS contract for every
handler behind ``("req", (rank, nonce), seq, msg)`` — not a style
rule (the mark-exact lost-gradient bug and the closed-channel hang
were both protocol hazards of exactly this shape).  This rule keeps
the contract machine-checked from the extracted protocol table
(:mod:`mxnet_tpu.analysis.protocol`):

* every dispatched op (``_handle`` chains) and every ``register_op``
  extension carries a ``# protocol: replay(<guard>)`` declaration;
* guards come from the fixed vocabulary (pure / idempotent /
  dedup-window / per-generation);
* a dispatch branch declared ``pure`` that writes ``self.*`` state is
  flagged — undeclared mutation behind replay;
* every core op dispatched by ``KVStoreServer._handle`` appears in
  ``register_op``'s reserved tuple (else an extension could shadow
  it);
* every literal client request site (``.request((op, ...))`` /
  ``.submit`` / ``_oneshot_request``) names a dispatched/registered
  op — a typo'd op fails lint, not a live job;
* every literal ``srv.<x>`` span name is a registered op or is
  declared ``# protocol: span(phase)`` (an internal handler phase).
"""
from __future__ import annotations

from .. import protocol
from ..lint import Finding

_CORE_OWNER = "KVStoreServer"


class _ProtocolOpsRule:
    name = "protocol-op"

    def check_file(self, ctx, project):
        table = protocol.extract_file(ctx)
        project.scratch.setdefault("protocol", []).append(table)
        return ()

    def finalize(self, project):
        tables = project.scratch.get("protocol", [])
        table = protocol.ProtocolTable()
        for t in tables:
            table.merge(t)
        if not (table.ops or table.clients or table.spans):
            return

        for path, line, msg in table.bad_decls:
            yield Finding(rule=self.name, path=path, line=line,
                          message=msg)

        seen = set()
        for op in table.ops:
            if (op.kind, op.name, op.path, op.line) in seen:
                continue
            seen.add((op.kind, op.name, op.path, op.line))
            if op.decl is None or op.decl.replay is None:
                yield Finding(
                    rule=self.name, path=op.path, line=op.line,
                    message="wire op %r has no replay-safety "
                    "declaration — a reconnect REPLAYS the unacked "
                    "window into this handler; declare why that is "
                    "safe: '# protocol: replay(pure|idempotent|"
                    "dedup-window|per-generation) reply(<shape>)'"
                    % op.name)

        for name, path, line, what in table.impure:
            yield Finding(
                rule=self.name, path=path, line=line,
                message="op %r is declared replay(pure) but its "
                "dispatch branch mutates server state (%s) — "
                "undeclared mutation behind replay; declare the real "
                "guard (idempotent / dedup-window / per-generation) "
                "or hoist the mutation" % (name, what))

        reserved = set(table.reserved)
        if reserved:
            for op in table.ops:
                if op.kind == "core" and op.owner == _CORE_OWNER \
                        and op.name not in reserved:
                    yield Finding(
                        rule=self.name, path=op.path, line=op.line,
                        message="core op %r is dispatched but missing "
                        "from register_op's reserved tuple — an "
                        "extension could shadow it; add it to the "
                        "reserved core-op list" % op.name)

        if not table.ops:
            # no dispatch table in scope (a lone client-side fixture
            # file): nothing to validate sites/spans against
            return
        known = table.op_names() | {protocol.ENVELOPE_OP}
        for site in table.clients:
            if site.op not in known:
                yield Finding(
                    rule=self.name, path=site.path, line=site.line,
                    message="client sends op %r via %s but no server "
                    "dispatches or registers it — a typo'd/retired op "
                    "would fail only at runtime on a live cluster"
                    % (site.op, site.via))

        for span in table.spans:
            suffix = span.name[len("srv."):]
            if span.phase or suffix in known:
                continue
            yield Finding(
                rule=self.name, path=span.path, line=span.line,
                message="span %r uses the srv.<op> namespace but %r "
                "is not a registered wire op — name it after the op "
                "it serves, or declare '# protocol: span(phase)' if "
                "it is an internal handler phase"
                % (span.name, suffix))


RULE = _ProtocolOpsRule()
