"""bare-thread: thread targets must propagate crashes.

A daemon thread that dies with an unhandled exception takes its
traceback to stderr and nothing else: the consumer blocks forever on a
queue/event the producer will never signal — the failure mode
PrefetchingIter's sticky ``_ProducerError`` pattern exists to prevent
(a parked exception the consumer re-raises on its next call).

This rule flags every ``threading.Thread(target=...)`` whose target
function contains no broad exception capture (``except Exception`` /
``except BaseException`` / bare ``except``).  Catching broadly at a
thread boundary is CORRECT — the point is what the handler does with
it: park the error where the consumer looks (``self._err``, a queue
sentinel, channel poison).  A target whose crash is already observable
some other way (e.g. it holds the only socket, so death surfaces as
ECONNRESET at every client) documents that with
``# analysis: allow(bare-thread): <reason>``.
"""
from __future__ import annotations

import ast

from ..lint import Finding

_BROAD = {"Exception", "BaseException"}


def _has_broad_handler(func_node) -> bool:
    for node in ast.walk(func_node):
        if not isinstance(node, ast.ExceptHandler):
            continue
        t = node.type
        if t is None:
            return True
        names = t.elts if isinstance(t, ast.Tuple) else [t]
        for n in names:
            if isinstance(n, ast.Name) and n.id in _BROAD:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _BROAD:
                return True
    return False


class _Scope:
    def __init__(self, node, cls, funcs):
        self.node = node
        self.cls = cls          # enclosing class name or None
        self.funcs = funcs      # name -> FunctionDef visible here


def _thread_call(node, thread_aliases):
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread" \
            and isinstance(f.value, ast.Name) \
            and f.value.id in thread_aliases:
        return True
    if isinstance(f, ast.Name) and f.id == "Thread":
        return True
    return False


class _BareThreadRule:
    name = "bare-thread"

    def check_file(self, ctx, project):
        thread_aliases = {"threading"}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        thread_aliases.add(a.asname or a.name)

        # collect function defs with their lexical context
        module_funcs = {}
        class_methods = {}      # class name -> {method name -> def}
        nested = {}             # outer FunctionDef -> {name -> def}

        def collect(node, cls, outer):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    class_methods.setdefault(child.name, {})
                    collect(child, child.name, None)
                elif isinstance(child,
                                (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if outer is not None:
                        nested.setdefault(outer, {})[child.name] = child
                    elif cls is not None:
                        class_methods[cls][child.name] = child
                    else:
                        module_funcs[child.name] = child
                    collect(child, cls, child)
                else:
                    collect(child, cls, outer)

        collect(ctx.tree, None, None)

        findings = []

        def visit(node, cls, outer_chain):
            for child in ast.iter_child_nodes(node):
                nxt_cls, nxt_chain = cls, outer_chain
                if isinstance(child, ast.ClassDef):
                    nxt_cls, nxt_chain = child.name, []
                elif isinstance(child,
                                (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nxt_chain = outer_chain + [child]
                if isinstance(child, ast.Call) \
                        and _thread_call(child, thread_aliases):
                    findings.extend(self._check_target(
                        ctx, child, cls, outer_chain,
                        module_funcs, class_methods, nested))
                visit(child, nxt_cls, nxt_chain)

        visit(ctx.tree, None, [])
        return findings

    def _check_target(self, ctx, call, cls, outer_chain,
                      module_funcs, class_methods, nested):
        target = next((kw.value for kw in call.keywords
                       if kw.arg == "target"), None)
        if target is None:
            return [Finding(
                rule=self.name, path=ctx.relpath, line=call.lineno,
                message="threading.Thread with no resolvable target= — "
                "cannot verify crash propagation; pass target= or "
                "annotate")]
        func = None
        if isinstance(target, ast.Name):
            for outer in reversed(outer_chain):
                func = nested.get(outer, {}).get(target.id)
                if func is not None:
                    break
            if func is None and cls is not None:
                func = class_methods.get(cls, {}).get(target.id)
            if func is None:
                func = module_funcs.get(target.id)
        elif isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id in ("self", "cls") and cls is not None:
            func = class_methods.get(cls, {}).get(target.attr)
        if func is None:
            return [Finding(
                rule=self.name, path=ctx.relpath, line=call.lineno,
                message="thread target could not be resolved statically "
                "— cannot verify crash propagation; use a local def / "
                "method reference or annotate")]
        if _has_broad_handler(func):
            return []
        return [Finding(
            rule=self.name, path=ctx.relpath, line=call.lineno,
            message="thread target %r has no broad exception capture: "
            "an unexpected crash kills the thread silently and hangs "
            "its consumers — park failures for the consumer (the "
            "sticky-error pattern PrefetchingIter uses) or annotate "
            "why thread death is already observable" % target_name(
                target))]


def target_name(target):
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return ast.dump(target)


RULE = _BareThreadRule()
