"""``python -m mxnet_tpu.analysis`` — the static-analysis CI gate.

Default run lints the installed ``mxnet_tpu`` package (plus the
whole-package checks: static lock-order cycles, blocking-under-lock,
the wire-protocol conformance table, knob-registry drift against
docs/ROBUSTNESS.md) and reports findings; ``--strict`` makes any
unannotated finding fatal — that form is the ``analysis`` gate in
ci/run_ci.sh.  Explicit paths lint those files/directories instead
(the fixture tests drive this).

``--knob-table`` / ``--protocol-table`` print the generated markdown
tables docs/ROBUSTNESS.md and docs/PROTOCOL.md fold in;
``--codec-table`` prints the generated hot-op block
mxnet_tpu/wirecodec.py folds in; ``--check`` fails (exit 2) when any
generated copy is STALE instead of silently regenerating — the drift
gate ci/run_ci.sh runs next to ``--strict``.
``--json`` emits one finding per line (the Finding dataclass fields
verbatim) so CI and the autotune journal consume findings without
scraping text.
"""
from __future__ import annotations

import argparse
import sys

from . import knobs, protocol
from .lint import lint_paths, package_root


def _run_explorer(args) -> int:
    """--explore / --replay: the interleaving-exploration entrypoint
    (ISSUE 20).  Exit 1 on any finding — CI runs the seven real
    scenarios expecting 0 and the seeded bugs expecting 1."""
    from ..base import env as _env
    from . import sched
    if args.replay:
        r = sched.replay(args.replay, journal_dir=args.journal_dir)
        print("replay %s: scenario=%s %d decisions, %d finding(s)"
              % (args.replay, r.scenario, r.ops, len(r.findings)))
        for kind, detail in r.findings:
            print("[%s] %s" % (kind, detail))
        return 1 if r.findings else 0
    schedules = args.schedules if args.schedules is not None else \
        int(_env("MXNET_SCHED_SCHEDULES", 20))
    seed = args.seed if args.seed is not None else \
        int(_env("MXNET_SCHED_SEED", 0))
    res = sched.explore(args.explore, schedules=schedules, seed=seed,
                        depth=args.depth, journal_dir=args.journal_dir)
    ran = len(res.schedules)
    ops = sum(r.ops for r in res.schedules)
    if not res.findings:
        print("explore %s: %d schedules (seed %d, %d decisions) clean"
              % (args.explore, ran, seed, ops))
        return 0
    bad = res.failing
    print("explore %s: findings at schedule %d of %d (seed %d); "
          "journal: %s" % (args.explore, bad.index, ran, seed,
                           bad.journal_path))
    for kind, detail in bad.findings:
        print("[%s] %s" % (kind, detail))
    print("replay with: python -m mxnet_tpu.analysis --replay %s"
          % bad.journal_path)
    return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="framework-aware lint + invariant gates "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the mxnet_tpu "
                         "package + whole-package checks)")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on any unannotated finding "
                         "(the CI gate mode)")
    ap.add_argument("--json", action="store_true",
                    help="one finding per line as JSON (Finding "
                         "dataclass fields; suppressed ones included "
                         "with suppressed=true)")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the generated markdown knob table for "
                         "docs/ROBUSTNESS.md and exit")
    ap.add_argument("--protocol-table", action="store_true",
                    help="print the generated wire-protocol op table "
                         "for docs/PROTOCOL.md and exit")
    ap.add_argument("--codec-table", action="store_true",
                    help="print the generated hot-op codec block for "
                         "mxnet_tpu/wirecodec.py and exit")
    ap.add_argument("--check", action="store_true",
                    help="fail (exit 2) when a generated table "
                         "(ROBUSTNESS.md knobs, PROTOCOL.md ops, "
                         "wirecodec.py hot-op codec block) is stale — "
                         "the CI drift gate")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--explore", metavar="SCENARIO",
                    help="run SCENARIO under N seeded controlled "
                         "schedules (PCT) with race/deadlock/"
                         "starvation detection; exit 1 on any finding")
    ap.add_argument("--schedules", type=int, default=None,
                    help="schedules per --explore run (default "
                         "MXNET_SCHED_SCHEDULES)")
    ap.add_argument("--seed", type=int, default=None,
                    help="schedule seed (default MXNET_SCHED_SEED); "
                         "(seed, scenario, index) names a schedule")
    ap.add_argument("--depth", type=int, default=None,
                    help="PCT priority-change points + 1 (default "
                         "MXNET_SCHED_DEPTH)")
    ap.add_argument("--replay", metavar="JOURNAL",
                    help="re-execute a recorded schedule journal "
                         "decision for decision and exit 1 when its "
                         "findings reproduce")
    ap.add_argument("--journal-dir", default=None,
                    help="where schedule journals land (default "
                         "MXNET_SCHED_JOURNAL_DIR); failing schedules "
                         "keep theirs, clean ones are deleted")
    ap.add_argument("--list-scenarios", action="store_true",
                    help="print the explorer scenario catalog and exit")
    args = ap.parse_args(argv)

    if args.list_scenarios:
        from . import scenarios as _scen
        for name in _scen.names():
            sc = _scen.get(name)
            first = sc.doc.splitlines()[0] if sc.doc else ""
            print("%-16s [%s] %s" % (name, sc.kind, first))
        return 0
    if args.explore or args.replay:
        return _run_explorer(args)

    if args.knob_table:
        print(knobs.markdown_table())
        return 0
    if args.protocol_table:
        print(protocol.markdown_table())
        return 0
    if args.codec_table:
        print(protocol.codec_table_source())
        return 0
    if args.check:
        problems = [p for p in (knobs.check_drift(package_root()),
                                protocol.check_drift(package_root()),
                                protocol.check_codec_drift(
                                    package_root()))
                    if p]
        for p in problems:
            print(p)
        if problems:
            return 2
        print("mxnet_tpu.analysis --check: generated doc tables are "
              "in sync")
        return 0
    if args.list_rules:
        from .rules import ALL_RULES
        for rule in ALL_RULES:
            doc = (sys.modules[type(rule).__module__].__doc__ or
                   "").strip().splitlines()
            print("%-20s %s" % (rule.name, doc[0] if doc else ""))
        return 0

    active, suppressed = lint_paths(args.paths or None)
    if args.json:
        import dataclasses
        import json
        for f in sorted(active + suppressed,
                        key=lambda f: (f.path, f.line, f.rule)):
            print(json.dumps(dataclasses.asdict(f), sort_keys=True))
    else:
        for f in sorted(active, key=lambda f: (f.path, f.line, f.rule)):
            print(f.render())
        print("mxnet_tpu.analysis: %d finding(s), %d suppressed by "
              "allow-annotations" % (len(active), len(suppressed)))
    if active:
        return 1 if args.strict else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
