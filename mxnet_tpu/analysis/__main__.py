"""``python -m mxnet_tpu.analysis`` — the static-analysis CI gate.

Default run lints the installed ``mxnet_tpu`` package (plus the
whole-package checks: static lock-order cycles, blocking-under-lock,
the wire-protocol conformance table, knob-registry drift against
docs/ROBUSTNESS.md) and reports findings; ``--strict`` makes any
unannotated finding fatal — that form is the ``analysis`` gate in
ci/run_ci.sh.  Explicit paths lint those files/directories instead
(the fixture tests drive this).

``--knob-table`` / ``--protocol-table`` print the generated markdown
tables docs/ROBUSTNESS.md and docs/PROTOCOL.md fold in;
``--codec-table`` prints the generated hot-op block
mxnet_tpu/wirecodec.py folds in; ``--check`` fails (exit 2) when any
generated copy is STALE instead of silently regenerating — the drift
gate ci/run_ci.sh runs next to ``--strict``.
``--json`` emits one finding per line (the Finding dataclass fields
verbatim) so CI and the autotune journal consume findings without
scraping text.
"""
from __future__ import annotations

import argparse
import sys

from . import knobs, protocol
from .lint import lint_paths, package_root


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="framework-aware lint + invariant gates "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the mxnet_tpu "
                         "package + whole-package checks)")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on any unannotated finding "
                         "(the CI gate mode)")
    ap.add_argument("--json", action="store_true",
                    help="one finding per line as JSON (Finding "
                         "dataclass fields; suppressed ones included "
                         "with suppressed=true)")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the generated markdown knob table for "
                         "docs/ROBUSTNESS.md and exit")
    ap.add_argument("--protocol-table", action="store_true",
                    help="print the generated wire-protocol op table "
                         "for docs/PROTOCOL.md and exit")
    ap.add_argument("--codec-table", action="store_true",
                    help="print the generated hot-op codec block for "
                         "mxnet_tpu/wirecodec.py and exit")
    ap.add_argument("--check", action="store_true",
                    help="fail (exit 2) when a generated table "
                         "(ROBUSTNESS.md knobs, PROTOCOL.md ops, "
                         "wirecodec.py hot-op codec block) is stale — "
                         "the CI drift gate")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.knob_table:
        print(knobs.markdown_table())
        return 0
    if args.protocol_table:
        print(protocol.markdown_table())
        return 0
    if args.codec_table:
        print(protocol.codec_table_source())
        return 0
    if args.check:
        problems = [p for p in (knobs.check_drift(package_root()),
                                protocol.check_drift(package_root()),
                                protocol.check_codec_drift(
                                    package_root()))
                    if p]
        for p in problems:
            print(p)
        if problems:
            return 2
        print("mxnet_tpu.analysis --check: generated doc tables are "
              "in sync")
        return 0
    if args.list_rules:
        from .rules import ALL_RULES
        for rule in ALL_RULES:
            doc = (sys.modules[type(rule).__module__].__doc__ or
                   "").strip().splitlines()
            print("%-20s %s" % (rule.name, doc[0] if doc else ""))
        return 0

    active, suppressed = lint_paths(args.paths or None)
    if args.json:
        import dataclasses
        import json
        for f in sorted(active + suppressed,
                        key=lambda f: (f.path, f.line, f.rule)):
            print(json.dumps(dataclasses.asdict(f), sort_keys=True))
    else:
        for f in sorted(active, key=lambda f: (f.path, f.line, f.rule)):
            print(f.render())
        print("mxnet_tpu.analysis: %d finding(s), %d suppressed by "
              "allow-annotations" % (len(active), len(suppressed)))
    if active:
        return 1 if args.strict else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
