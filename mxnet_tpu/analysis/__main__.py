"""``python -m mxnet_tpu.analysis`` — the static-analysis CI gate.

Default run lints the installed ``mxnet_tpu`` package (plus the
whole-package checks: static lock-order cycles, knob-registry drift
against docs/ROBUSTNESS.md) and reports findings; ``--strict`` makes
any unannotated finding fatal — that form is the ``analysis`` gate in
ci/run_ci.sh.  Explicit paths lint those files/directories instead
(the fixture tests drive this).  ``--knob-table`` prints the generated
markdown knob table to fold into docs/ROBUSTNESS.md.
"""
from __future__ import annotations

import argparse
import sys

from . import knobs
from .lint import lint_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="framework-aware lint + invariant gates "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the mxnet_tpu "
                         "package + whole-package checks)")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on any unannotated finding "
                         "(the CI gate mode)")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the generated markdown knob table for "
                         "docs/ROBUSTNESS.md and exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.knob_table:
        print(knobs.markdown_table())
        return 0
    if args.list_rules:
        from .rules import ALL_RULES
        for rule in ALL_RULES:
            doc = (sys.modules[type(rule).__module__].__doc__ or
                   "").strip().splitlines()
            print("%-14s %s" % (rule.name, doc[0] if doc else ""))
        return 0

    active, suppressed = lint_paths(args.paths or None)
    for f in sorted(active, key=lambda f: (f.path, f.line, f.rule)):
        print(f.render())
    print("mxnet_tpu.analysis: %d finding(s), %d suppressed by "
          "allow-annotations" % (len(active), len(suppressed)))
    if active:
        return 1 if args.strict else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
