"""AST lint driver: file loading, allow-annotations, rule dispatch.

The linter is deliberately framework-specific — it exists to keep the
invariants the codebase already paid for (sync-free hot path,
allowlisted unpickling, lock discipline, knob registry, sticky-error
threads) from rotting, not to restyle code.  Rules live in
:mod:`mxnet_tpu.analysis.rules`; each is a small object with a
``check_file(ctx, project)`` hook and an optional ``finalize(project)``
hook for whole-package facts (the static lock-order graph, knob
registry drift).

Suppression contract
--------------------
A finding is suppressed by an explicit annotation **with a reason** on
the flagged line or the line directly above it::

    data = blob.asnumpy()   # analysis: allow(host-sync): init path, once per process

    # analysis: allow(unsafe-pickle): trusted local checkpoint file
    states = pickle.load(fin)

``# analysis: allow-file(<rule>): <reason>`` anywhere in a file
suppresses the rule for the whole file.  An annotation with no reason
suppresses nothing — the reason is the point: it converts an invariant
violation into a documented, reviewable exception.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

_ANNOT_RE = re.compile(
    r"#\s*analysis:\s*allow(?P<file>-file)?"
    r"\((?P<rules>[a-zA-Z0-9_\-\s,]+)\)"
    r"(?::\s*(?P<reason>\S.*))?")

# The marker a non-package file (test fixture) uses to opt into the
# hot-path host-sync rule, which otherwise keys off the module path.
HOT_PATH_MARKER = "# analysis: hot-path"


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""
    rule: str
    path: str          # path as given (package-relative in package mode)
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def render(self) -> str:
        tag = " (allowed: %s)" % self.reason if self.suppressed else ""
        return "%s:%d: [%s] %s%s" % (
            self.path, self.line, self.rule, self.message, tag)


class FileContext:
    """Parsed view of one source file handed to every rule."""

    def __init__(self, path: Path, relpath: str):
        self.path = path
        self.relpath = relpath
        self.source = path.read_text()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        # line -> (set of rule names, reason); reasonless annotations are
        # kept (reason "") so strict reporting can point at them.
        self.allow_lines: Dict[int, Tuple[Set[str], str]] = {}
        self.allow_file: Dict[str, str] = {}
        for ln, text in enumerate(self.lines, start=1):
            m = _ANNOT_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            reason = (m.group("reason") or "").strip()
            if m.group("file"):
                for r in rules:
                    self.allow_file[r] = reason
            else:
                self.allow_lines[ln] = (rules, reason)
        self.hot_marker = HOT_PATH_MARKER in self.source

    def allowance(self, rule: str, line: int) -> Optional[str]:
        """Reason string if ``rule`` at ``line`` is annotated (the
        annotation may sit on the line itself or the line above);
        ``None`` when unannotated.  Empty reason -> not suppressed."""
        if self.allow_file.get(rule):
            return self.allow_file[rule]
        for ln in (line, line - 1):
            entry = self.allow_lines.get(ln)
            if entry and rule in entry[0]:
                return entry[1] or None
        return None


class Project:
    """Cross-file accumulator shared by all rules in one run."""

    def __init__(self, root: Path, is_package: bool):
        self.root = root
        self.is_package = is_package
        self.files: List[FileContext] = []
        # free-form per-rule scratch (lock graph, knob read sites, ...)
        self.scratch: Dict[str, object] = {}


def _iter_py_files(paths: Iterable[Path]):
    for p in paths:
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def _apply_allowances(ctx: FileContext, findings: Iterable[Finding]):
    for f in findings:
        reason = ctx.allowance(f.rule, f.line)
        if reason is not None:
            f.suppressed = True
            f.reason = reason
        yield f


def lint_paths(paths: Optional[List[Path]] = None):
    """Lint ``paths`` (default: the installed ``mxnet_tpu`` package).

    Returns ``(active, suppressed)`` finding lists.  Whole-package
    checks (static lock-order cycles, knob-registry drift against the
    docs) run whenever the lint root IS the package, so a fixture
    directory exercises per-site rules without dragging repo state in.
    """
    from .rules import ALL_RULES
    if paths:
        roots = [Path(p).resolve() for p in paths]
    else:
        roots = [package_root()]
    root = roots[0] if len(roots) == 1 else Path(".").resolve()
    is_package = len(roots) == 1 and roots[0].name == "mxnet_tpu" \
        and (roots[0] / "base.py").exists()
    project = Project(root=root, is_package=is_package)

    findings: List[Finding] = []
    for path in _iter_py_files(roots):
        try:
            rel = str(path.relative_to(root)) if path != root \
                else path.name
        except ValueError:
            rel = str(path)
        try:
            ctx = FileContext(path, rel)
        except (SyntaxError, UnicodeDecodeError) as exc:
            findings.append(Finding(
                rule="parse", path=rel, line=getattr(exc, "lineno", 1) or 1,
                message="could not parse: %s" % exc))
            continue
        project.files.append(ctx)
        for rule in ALL_RULES:
            findings.extend(
                _apply_allowances(ctx, rule.check_file(ctx, project)))

    ctx_by_rel = {c.relpath: c for c in project.files}
    for rule in ALL_RULES:
        final = getattr(rule, "finalize", None)
        if final is None:
            continue
        for f in final(project):
            ctx = ctx_by_rel.get(f.path)
            if ctx is not None:
                f = next(iter(_apply_allowances(ctx, [f])))
            findings.append(f)

    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    return active, suppressed


def run_lint(paths: Optional[List[Path]] = None) -> List[Finding]:
    """Convenience wrapper: active (unsuppressed) findings only."""
    return lint_paths(paths)[0]
