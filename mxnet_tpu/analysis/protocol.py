"""Machine-readable view of the distributed-plane wire protocol.

The op table is EXTRACTED from the AST, never hand-maintained: the
core envelope dispatch (``KVStoreServer._handle``'s ``op ==`` /
``op in (...)`` chain), the mesh fan-in dispatch
(``_MeshLeader._handle``), every ``register_op`` extension site (the
serving tier), the reserved-core-op tuple inside ``register_op``
itself, every client request site (``.request((op, ...))`` /
``.submit((op, ...))`` / ``_oneshot_request(addr, (op, ...))`` with a
literal op), and every literal ``srv.*`` span name.  Each handler
carries a structured declaration comment on its dispatch line (or the
line above)::

    if op == "push":   # protocol: replay(dedup-window) reply(none)

    server.register_op("predict", fn)  # protocol: replay(pure) reply(batch)

    sp = _tr.span_begin("srv.failover_rebuild")  # protocol: span(phase)

``replay(<guard>)`` declares WHY the handler is safe behind the
exactly-once envelope's replay (a reconnect replays the whole unacked
window):

* ``pure`` — no observable server-state mutation; re-running is free.
  Statically cross-checked: a dispatch branch declared pure that
  writes ``self.*`` state is a finding.
* ``idempotent`` — mutates, but replay converges to the same state by
  construction (first-init-wins, verbatim assign, newest-seq-wins
  banks, bseq-numbered barriers, roster joins).
* ``dedup-window`` — NOT intrinsically replay-safe (a re-applied push
  doubles a gradient); correct only because the per-client
  ``(client_id, seq)`` dedup window serves replays from cache.  These
  handlers must never be reachable outside the envelope.
* ``per-generation`` — first delivery per ``(key, generation)`` wins;
  duplicates ack without re-applying (handoff/handoff_state).

``reply(<shape>)`` names the reply payload for the generated protocol
table (docs/PROTOCOL.md) the way ``--knob-table`` feeds ROBUSTNESS.md.
``span(phase)`` declares a ``srv.*`` span that is an internal phase of
a handler, not an envelope op of its own.

``codec(binary)`` marks a HOT op: its envelopes (and replies) ride the
registry-generated binary frame codec (:mod:`mxnet_tpu.wirecodec`)
instead of pickle once a connection has negotiated it.  The codec's
op set is GENERATED from these declarations (``--codec-table`` emits
the literal block wirecodec.py folds in between its
``codec-table:begin/end`` markers); the ``codec-coverage`` rule and
``--check`` fail when the generated table drifts from the registry.

The projection cannot drift from the code because it IS the code; the
``protocol-op`` rule fails CI when a handler, client site or span
falls outside it.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Tuple

DOCS_BEGIN = "<!-- protocol-table:begin (generated:"
DOCS_END = "<!-- protocol-table:end -->"

# markers of the generated hot-op block inside mxnet_tpu/wirecodec.py
# (python source, so the markers are comments, not HTML)
CODEC_BEGIN = "# codec-table:begin (generated:"
CODEC_END = "# codec-table:end"

REPLAY_GUARDS = ("pure", "idempotent", "dedup-window", "per-generation")

# the only codec the registry generates today; the field is a vocabulary
# so a typo'd value is a bad_decl finding, not a silently-pickled op
CODEC_KINDS = ("binary",)

# the wire envelope itself — dispatch machinery, not an op
ENVELOPE_OP = "req"

_PROTO_RE = re.compile(r"#\s*protocol:\s*(?P<body>\S.*)")
_FIELD_RE = re.compile(r"(?P<key>[a-z-]+)\((?P<val>[^()]*)\)")


@dataclasses.dataclass
class Declaration:
    """One parsed ``# protocol:`` comment."""
    line: int
    replay: Optional[str] = None
    reply: Optional[str] = None
    span: Optional[str] = None
    codec: Optional[str] = None
    unknown: Tuple[str, ...] = ()


@dataclasses.dataclass
class OpInfo:
    """One wire op: where it is dispatched/registered and its
    declaration."""
    name: str
    kind: str               # "core" | "mesh" | "extension"
    path: str
    line: int
    owner: str              # enclosing class of the dispatch/registration
    decl: Optional[Declaration] = None

    @property
    def replay(self) -> Optional[str]:
        return self.decl.replay if self.decl else None

    @property
    def reply(self) -> str:
        return (self.decl.reply if self.decl and self.decl.reply
                else "—")

    @property
    def codec(self) -> Optional[str]:
        return self.decl.codec if self.decl else None


@dataclasses.dataclass
class ClientSite:
    """One literal client request site."""
    op: str
    path: str
    line: int
    via: str                # request | submit | _oneshot_request


@dataclasses.dataclass
class SpanSite:
    """One literal ``srv.*`` span name."""
    name: str
    path: str
    line: int
    phase: bool             # declared span(phase)


@dataclasses.dataclass
class ProtocolTable:
    ops: List[OpInfo] = dataclasses.field(default_factory=list)
    clients: List[ClientSite] = dataclasses.field(default_factory=list)
    spans: List[SpanSite] = dataclasses.field(default_factory=list)
    reserved: List[str] = dataclasses.field(default_factory=list)
    # dispatch branches declared pure that mutate self state:
    # (op, path, line, what)
    impure: List[Tuple[str, str, int, str]] = \
        dataclasses.field(default_factory=list)
    bad_decls: List[Tuple[str, int, str]] = \
        dataclasses.field(default_factory=list)

    def op_names(self) -> set:
        return {o.name for o in self.ops}

    def merge(self, other: "ProtocolTable") -> None:
        self.ops.extend(other.ops)
        self.clients.extend(other.clients)
        self.spans.extend(other.spans)
        self.reserved.extend(other.reserved)
        self.impure.extend(other.impure)
        self.bad_decls.extend(other.bad_decls)


def _comment_lines(source: str):
    """(line, comment-text) for REAL comment tokens only — a line scan
    would also match protocol examples inside docstrings (this very
    module's, for one)."""
    import io
    import tokenize
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError):
        return


def parse_declarations(source) -> Dict[int, Declaration]:
    """``# protocol:`` comments by line number (1-based)."""
    out: Dict[int, Declaration] = {}
    for ln, text in _comment_lines(source):
        m = _PROTO_RE.search(text)
        if not m:
            continue
        decl = Declaration(line=ln)
        unknown = []
        for fm in _FIELD_RE.finditer(m.group("body")):
            key, val = fm.group("key"), fm.group("val").strip()
            if key == "replay":
                decl.replay = val
            elif key == "reply":
                decl.reply = val
            elif key == "span":
                decl.span = val
            elif key == "codec":
                decl.codec = val
            else:
                unknown.append(key)
        decl.unknown = tuple(unknown)
        out[ln] = decl
    return out


def _decl_at(decls: Dict[int, Declaration],
             line: int) -> Optional[Declaration]:
    """The declaration covering ``line`` (the line itself or the line
    directly above — same placement contract as allow-annotations)."""
    for ln in (line, line - 1):
        if ln in decls:
            return decls[ln]
    return None


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _op_literals(node) -> List[str]:
    """Strings of ``op == "x"`` / ``op in ("x", "y")`` compares."""
    if not (isinstance(node, ast.Compare) and len(node.ops) == 1
            and isinstance(node.left, ast.Name)
            and node.left.id == "op"
            and isinstance(node.ops[0], (ast.Eq, ast.In))):
        return []
    comp = node.comparators[0]
    if isinstance(comp, ast.Tuple):
        vals = [_const_str(e) for e in comp.elts]
        return [v for v in vals if v is not None]
    v = _const_str(comp)
    return [v] if v is not None else []


def _self_mutations(stmts) -> List[Tuple[int, str]]:
    """Direct writes to self-rooted state inside a dispatch branch —
    the static cross-check behind ``replay(pure)``.  Shallow by
    design: helper calls carry their own declarations."""
    def rooted_self(node):
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return isinstance(node, ast.Name) and node.id == "self"

    out = []
    for stmt in stmts:
        for node in ast.walk(stmt):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = node.targets
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr.startswith("_apply") \
                    and rooted_self(node.func):
                out.append((node.lineno,
                            "call to self.%s" % node.func.attr))
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)) \
                        and rooted_self(t):
                    out.append((t.lineno, ast.unparse(t)))
    return out


_MESH_CLASSES = ("_MeshLeader",)
_TRACING_FNS = ("span", "span_begin", "instant", "add_span")


class _Extractor(ast.NodeVisitor):
    def __init__(self, ctx):
        self.ctx = ctx
        self.table = ProtocolTable()
        self.decls = parse_declarations(ctx.source)
        self.cls: Optional[str] = None
        self.fn: Optional[str] = None

    def run(self) -> ProtocolTable:
        self.visit(self.ctx.tree)
        for decl in self.decls.values():
            for key in decl.unknown:
                self.table.bad_decls.append(
                    (self.ctx.relpath, decl.line,
                     "unknown protocol field %r (expected replay/"
                     "reply/span/codec)" % key))
            if decl.replay is not None \
                    and decl.replay not in REPLAY_GUARDS:
                self.table.bad_decls.append(
                    (self.ctx.relpath, decl.line,
                     "unknown replay guard %r (expected one of %s)"
                     % (decl.replay, ", ".join(REPLAY_GUARDS))))
            if decl.codec is not None \
                    and decl.codec not in CODEC_KINDS:
                self.table.bad_decls.append(
                    (self.ctx.relpath, decl.line,
                     "unknown codec %r (expected one of %s)"
                     % (decl.codec, ", ".join(CODEC_KINDS))))
        return self.table

    def visit_ClassDef(self, node):
        prev, self.cls = self.cls, node.name
        self.generic_visit(node)
        self.cls = prev

    def _visit_fn(self, node):
        prev, self.fn = self.fn, node.name
        if node.name == "_handle":
            self._extract_dispatch(node)
        elif node.name == "register_op":
            self._extract_reserved(node)
        self.generic_visit(node)
        self.fn = prev

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _extract_dispatch(self, fn_node):
        kind = "mesh" if self.cls in _MESH_CLASSES else "core"
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.If):
                continue
            names = _op_literals(node.test)
            if not names:
                continue
            decl = _decl_at(self.decls, node.test.lineno)
            for name in names:
                info = OpInfo(name=name, kind=kind,
                              path=self.ctx.relpath,
                              line=node.test.lineno,
                              owner=self.cls or "<module>", decl=decl)
                self.table.ops.append(info)
                if decl is not None and decl.replay == "pure":
                    for ln, what in _self_mutations(node.body):
                        self.table.impure.append(
                            (name, self.ctx.relpath, ln, what))

    def _extract_reserved(self, fn_node):
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Compare) \
                    and isinstance(node.ops[0], ast.In) \
                    and isinstance(node.comparators[0], ast.Tuple):
                vals = [_const_str(e)
                        for e in node.comparators[0].elts]
                self.table.reserved.extend(
                    v for v in vals if v is not None)
                return

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "register_op" and node.args:
                name = _const_str(node.args[0])
                if name is not None:
                    self.table.ops.append(OpInfo(
                        name=name, kind="extension",
                        path=self.ctx.relpath, line=node.lineno,
                        owner=self.cls or "<module>",
                        decl=_decl_at(self.decls, node.lineno)))
            elif f.attr in ("request", "submit") and node.args:
                self._client_site(node.args[0], f.attr, node.lineno)
            elif f.attr == "_oneshot_request" and len(node.args) >= 2:
                self._client_site(node.args[1], f.attr, node.lineno)
            elif f.attr in _TRACING_FNS and node.args:
                name = _const_str(node.args[0])
                if name is not None and name.startswith("srv."):
                    decl = _decl_at(self.decls, node.lineno)
                    self.table.spans.append(SpanSite(
                        name=name, path=self.ctx.relpath,
                        line=node.lineno,
                        phase=bool(decl and decl.span == "phase")))
        self.generic_visit(node)

    def _client_site(self, arg, via, line):
        if isinstance(arg, (ast.Tuple, ast.List)) and arg.elts:
            op = _const_str(arg.elts[0])
            if op is not None:
                self.table.clients.append(ClientSite(
                    op=op, path=self.ctx.relpath, line=line, via=via))


def extract_file(ctx) -> ProtocolTable:
    """Protocol facts of one parsed file (analysis.lint.FileContext)."""
    return _Extractor(ctx).run()


def extract_package(root=None) -> ProtocolTable:
    """The protocol table of the package at ``root`` (default: the
    installed one) — drives --protocol-table and the docs drift
    check."""
    from pathlib import Path
    from .lint import FileContext, package_root
    root = Path(root) if root is not None else package_root()
    table = ProtocolTable()
    for path in sorted(root.rglob("*.py")):
        try:
            ctx = FileContext(path, str(path.relative_to(root)))
        except (SyntaxError, UnicodeDecodeError):
            continue
        table.merge(extract_file(ctx))
    return table


def check_drift(package_root) -> Optional[str]:
    """Stale-table drift check (``--check``): the docs/PROTOCOL.md
    NEXT TO ``package_root`` must carry the op table extracted from
    THAT tree verbatim between its markers.  None when in sync; an
    error string otherwise (a missing docs file counts — every wire
    op is born documented)."""
    from pathlib import Path
    root = Path(package_root).resolve()
    docs_path = root.parent / "docs" / "PROTOCOL.md"
    if not docs_path.exists():
        if not (root.parent / "docs").exists():
            return None   # installed package without a docs checkout
        return ("docs/PROTOCOL.md does not exist: generate it around "
                "`python -m mxnet_tpu.analysis --protocol-table`")
    if markdown_table(extract_package(root)) not in \
            docs_path.read_text():
        return ("docs/PROTOCOL.md protocol table is STALE: regenerate "
                "with `python -m mxnet_tpu.analysis --protocol-table` "
                "and paste it over the protocol-table:begin/end block")
    return None


def codec_ops(table: Optional[ProtocolTable] = None) -> List[str]:
    """Sorted names of the ops declared ``codec(binary)`` — the hot-op
    set the generated wire codec covers."""
    if table is None:
        table = extract_package()
    return sorted({o.name for o in table.ops if o.codec == "binary"})


def codec_fingerprint(names) -> str:
    """Fingerprint of a hot-op name list — what
    CODEC_TABLE_FINGERPRINT must equal for the sorted declared set."""
    import hashlib
    return hashlib.sha256(
        "\n".join(sorted(names)).encode()).hexdigest()[:12]


def codec_table_source(table: Optional[ProtocolTable] = None) -> str:
    """The generated hot-op block mxnet_tpu/wirecodec.py folds in
    between its codec-table markers (regenerate with
    ``python -m mxnet_tpu.analysis --codec-table``).  The fingerprint
    pins the exact op set, so hand-edits drift-fail even when the
    frozenset itself still parses."""
    names = codec_ops(table)
    fp = codec_fingerprint(names)
    lines = [CODEC_BEGIN + " python -m mxnet_tpu.analysis"
             " --codec-table)",
             "HOT_OPS = frozenset({"]
    lines.extend('    "%s",' % n for n in names)
    lines.append("})")
    lines.append('CODEC_TABLE_FINGERPRINT = "%s"' % fp)
    lines.append(CODEC_END)
    return "\n".join(lines)


def check_codec_drift(package_root) -> Optional[str]:
    """Stale-codec drift check (``--check``): mxnet_tpu/wirecodec.py
    must carry the hot-op block generated from the registry verbatim
    between its codec-table markers.  None when in sync; an error
    string otherwise (a missing module counts — the codec is born
    registry-generated)."""
    from pathlib import Path
    root = Path(package_root).resolve()
    path = root / "wirecodec.py"
    if not path.exists():
        return ("mxnet_tpu/wirecodec.py does not exist: generate its "
                "hot-op table with `python -m mxnet_tpu.analysis "
                "--codec-table`")
    if codec_table_source(extract_package(root)) not in \
            path.read_text():
        return ("mxnet_tpu/wirecodec.py codec table is STALE: "
                "regenerate with `python -m mxnet_tpu.analysis "
                "--codec-table` and paste it over the "
                "codec-table:begin/end block")
    return None


def markdown_table(table: Optional[ProtocolTable] = None) -> str:
    """The protocol table docs/PROTOCOL.md folds in (regenerate with
    ``python -m mxnet_tpu.analysis --protocol-table``)."""
    if table is None:
        table = extract_package()
    lines = [
        DOCS_BEGIN + " python -m mxnet_tpu.analysis"
        " --protocol-table) -->",
        "| op | kind | replay guard | reply | codec | handler |",
        "|----|------|--------------|-------|-------|---------|",
    ]
    seen = set()
    for op in sorted(table.ops, key=lambda o: (o.kind, o.name, o.line)):
        key = (op.kind, op.name)
        if key in seen:
            continue   # an `op in (...)` chain names one line per op
        seen.add(key)
        # no line numbers: the docs copy must only drift when the
        # PROTOCOL changes, not when unrelated edits shift a file
        lines.append("| `%s` | %s | %s | %s | %s | `%s` (%s) |" % (
            op.name, op.kind, op.replay or "**undeclared**",
            op.reply.replace("|", "\\|"), op.codec or "pickle",
            op.path, op.owner))
    phases = sorted({s.name for s in table.spans if s.phase})
    if phases:
        lines.append("")
        lines.append("Internal phase spans (`span(phase)` — handler "
                     "sub-phases, not envelope ops): "
                     + ", ".join("`%s`" % p for p in phases))
    lines.append(DOCS_END)
    return "\n".join(lines)
