"""Machine-readable view of the MXNET_* environment-knob registry.

The single source of truth stays ``base.declare_env`` — every knob the
framework consults is declared there with a type, default and doc
string, and ``base.env`` resolves reads through it.  This module is the
analysis-facing projection: a typed :class:`Knob` table for tooling,
the generated markdown table that docs/ROBUSTNESS.md folds in (between
the ``knob-table`` markers), and the drift check the ``env-knob`` lint
rule runs in package mode.  Two registries would immediately drift
against each other; a projection cannot.
"""
from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Tuple

DOCS_BEGIN = "<!-- knob-table:begin (generated:"
DOCS_END = "<!-- knob-table:end -->"


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    type: str
    default: object
    doc: str
    # search-space metadata from declare_env(tune=...) — None when the
    # knob declared no tune axis (mxnet_tpu.autotune derives its search
    # spaces exclusively from this field's presence)
    tune: Optional[dict] = None


def registry() -> Dict[str, Knob]:
    """Every declared knob, keyed by name (from base._ENV_FLAGS)."""
    from ..base import list_env_flags, list_env_tunables
    tunables = list_env_tunables()
    out = {}
    for name, (typ, default, doc) in sorted(list_env_flags().items()):
        out[name] = Knob(name=name, type=typ.__name__, default=default,
                         doc=" ".join(doc.split()),
                         tune=tunables.get(name))
    return out


def tune_summary(tune: Optional[dict]) -> str:
    """One-cell rendering of a knob's tune axis for the doc table."""
    if not tune:
        return "—"
    if tune.get("kind") == "choice":
        return "{%s}" % ", ".join("%r" % c for c in tune["choices"])
    return "[%r, %r]%s" % (tune["min"], tune["max"],
                           " log" if tune.get("log") else "")


def markdown_table() -> str:
    """The knob table docs/ROBUSTNESS.md folds in (regenerate with
    ``python -m mxnet_tpu.analysis --knob-table``)."""
    lines = [
        DOCS_BEGIN + " python -m mxnet_tpu.analysis --knob-table) -->",
        "| knob | type | default | tunable | what it does |",
        "|------|------|---------|---------|--------------|",
    ]
    for knob in registry().values():
        lines.append("| `%s` | %s | `%r` | %s | %s |" % (
            knob.name, knob.type, knob.default,
            tune_summary(knob.tune), knob.doc or "—"))
    lines.append(DOCS_END)
    return "\n".join(lines)


def missing_in_text(text: str) -> List[str]:
    """Registered knobs absent from ``text``.  Matches the
    backtick-delimited form (`` `NAME` ``) the table and every doc
    mention use — a bare substring test would let a knob that is a
    PREFIX of another (RETRY_MAX vs RETRY_MAX_MS) pass on the longer
    name's row alone."""
    return [name for name in registry()
            if ("`%s`" % name) not in text]


def check_drift(package_root: Path) -> Optional[str]:
    """Stale-table drift check (``--check``): the generated knob table
    must appear VERBATIM between docs/ROBUSTNESS.md's markers — a knob
    added/retyped/redocumented without regenerating the table is a CI
    failure, not a silent regeneration.  None when in sync (or no docs
    checkout).  ``package_root`` locates the docs checkout only: the
    registry itself is runtime state of the IMPORTED package
    (base.declare_env), so this check is meaningful for the live tree,
    not an arbitrary other checkout."""
    docs_path = Path(package_root).resolve().parent / "docs" \
        / "ROBUSTNESS.md"
    if not docs_path.exists():
        if not docs_path.parent.exists():
            return None   # installed package without a docs checkout
        return ("docs/ROBUSTNESS.md does not exist but docs/ does: "
                "the knob table (`python -m mxnet_tpu.analysis "
                "--knob-table`) must live there")
    if markdown_table() not in docs_path.read_text():
        return ("docs/ROBUSTNESS.md knob table is STALE: regenerate "
                "with `python -m mxnet_tpu.analysis --knob-table` and "
                "paste it over the knob-table:begin/end block")
    return None


def docs_missing(package_root: Path) -> Tuple[List[str], Path]:
    """Registered knobs absent from docs/ROBUSTNESS.md.

    Returns ``(missing_names, docs_path)``; an empty list when the docs
    file does not exist (installed package, no repo checkout)."""
    docs_path = Path(package_root).resolve().parent / "docs" \
        / "ROBUSTNESS.md"
    if not docs_path.exists():
        return [], docs_path
    return missing_in_text(docs_path.read_text()), docs_path
