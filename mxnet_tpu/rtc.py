"""Runtime kernel compilation — the Pallas-backed ``mx.rtc`` analog.

The reference's ``mx.rtc`` compiles user CUDA source with NVRTC at
runtime (src/common/rtc.cc:35-67, python/mxnet/rtc.py).  CUDA source has
no meaning on TPU; the capability — "write a custom kernel at runtime and
call it on NDArrays" — maps to Pallas (docs/design/scope.md).  ``CudaModule``
therefore raises with migration guidance, and :class:`PallasKernel` is
the supported path: wrap a Pallas kernel function and call it on
NDArrays, with the same "runtime-compiled device kernel" ergonomics.
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray
from .ndarray.ndarray import array as nd_array


class CudaModule:
    """reference: rtc.py CudaModule (NVRTC). Unsupported on TPU."""

    def __init__(self, *a, **kw):
        raise MXNetError(
            "mx.rtc compiles CUDA source — not available on TPU. Port the "
            "kernel to Pallas and wrap it with mx.rtc.PallasKernel (see "
            "mxnet_tpu/ops/attention.py for a full example, "
            "docs/design/scope.md for the decision)")


CudaKernel = CudaModule  # same guidance for the old entry point


class PallasKernel:
    """Wrap a ``pallas_call``-based function as an NDArray op.

    ``fn(*jax_arrays, **attrs) -> jax array(s)`` — typically a closure
    over ``pl.pallas_call``.  The wrapper handles NDArray <-> jax.Array
    conversion and (like every registered op) records on the autograd
    tape, so kernels with a ``jax.custom_vjp`` are trainable.
    """

    def __init__(self, fn, name=None):
        if not callable(fn):
            raise MXNetError("PallasKernel: fn must be callable")
        self._fn = fn
        self.name = name or getattr(fn, "__name__", "pallas_kernel")

    def __call__(self, *args, **attrs):
        from .ndarray.ndarray import _invoke_fn
        inputs = [a if isinstance(a, NDArray) else nd_array(a)
                  for a in args]
        return _invoke_fn(self._fn, inputs, attrs)
