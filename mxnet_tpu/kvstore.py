"""KVStore: key-value parameter synchronization.

TPU-native re-design of the reference's kvstore stack (include/mxnet/
kvstore.h:45-397; src/kvstore/kvstore_local.h, comm.h, kvstore_dist.h).
The public API (init/push/pull/row_sparse_pull/set_optimizer/rank/
num_workers/barrier) is preserved; the transport is re-imagined:

* ``local`` / ``device`` — single-process aggregation.  The reference's
  CommCPU/CommDevice reduction trees (comm.h:90,462) collapse to a jnp sum
  (XLA emits the optimal reduction; cross-device copies ride ICI when the
  values live on different chips of a mesh).
* ``tpu`` — values that are sharded jax.Arrays over a device mesh are
  reduced with a jitted psum-style sum so gradient aggregation fuses and
  rides ICI collectives (SURVEY.md §5.8 north star).
* ``dist_sync`` — multi-process: the locally-reduced value is summed
  across processes (``distributed.allreduce_sum``, a host-side gather —
  gloo on CPU test clusters, DCN on pods) and every process applies the
  identical update.  This is the *compatibility* path giving the
  reference's exact worker-visible push/pull semantics; the *performance*
  path for multi-host training is ``Module(..., mesh=...)`` where GSPMD
  fuses the gradient psum into the jitted step (docs/design/kvstore.md).
  There are no parameter-server processes (kvstore_dist_server.h is
  intentionally not ported).
* ``dist_async`` — unsupported on TPU (documented; raises).

Update-on-kvstore (reference: server-side optimizer, kvstore_dist_server.h
:131) is supported: ``set_optimizer`` installs an Updater that runs the
fused update on the aggregated gradient.
"""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray
from . import optimizer as opt


def _key(k):
    return str(k)


class KVStore:
    """Single-process store (reference: KVStoreLocal, kvstore_local.h)."""

    def __init__(self, kvtype="local"):
        self.type = kvtype
        self._store: Dict[str, NDArray] = {}
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0
        # jitted multi-value reducer cache keyed by (n_values, shape, dtype)
        self._sum_cache = {}

    # -- identity -----------------------------------------------------------
    @property
    def rank(self) -> int:
        return jax.process_index() if self.type.startswith(("dist", "tpu")) \
            else 0

    @property
    def num_workers(self) -> int:
        return jax.process_count() if self.type.startswith(("dist", "tpu")) \
            else 1

    # -- init ----------------------------------------------------------------
    def init(self, key, value):
        keys, values = self._canon(key, value)
        for k, vs in zip(keys, values):
            if k in self._store:
                raise MXNetError(f"duplicate init of key {k}")
            val = vs[0]._data
            if self.type.startswith("dist") and self.num_workers > 1:
                # rank 0's init value is authoritative (reference: first
                # worker init wins at the server, kvstore_dist_server.h)
                from . import distributed as _dist
                val = jnp.asarray(_dist.broadcast_from_root(np.asarray(val)))
            self._store[k] = NDArray(val)

    # -- push/pull ------------------------------------------------------------
    def push(self, key, value, priority=0):
        """Aggregate value(s) into the store; runs updater if installed
        (reference: KVStoreLocal::PushImpl, kvstore_local.h:149).

        dist types additionally sum the locally-reduced value across all
        processes (the allreduce that replaces the reference's
        server-side MergeBuf aggregation, kvstore_dist_server.h:175-198);
        every process then applies the identical update, so the store
        stays replicated-consistent with no server round trip."""
        keys, values = self._canon(key, value)
        for k, vs in zip(keys, values):
            agg = self._reduce(vs)
            if self.type.startswith("dist") and self.num_workers > 1:
                from . import distributed as _dist
                agg = jnp.asarray(_dist.allreduce_sum(np.asarray(agg)))
            if k not in self._store:
                raise MXNetError(f"push to uninitialized key {k}")
            if self._updater is not None:
                self._updater(self._key_int(k), NDArray(agg), self._store[k])
            else:
                # no updater: store holds the reduced value (reference:
                # kvstore_local.h:173 local = merged — assign, don't add)
                self._store[k]._set_data(agg)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value to out array(s)
        (reference: KVStoreLocal::PullImpl, kvstore_local.h:188)."""
        assert out is not None
        keys, outs = self._canon(key, out)
        for k, os_ in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"pull of uninitialized key {k}")
            src = self._store[k]
            for o in os_:
                o._set_data(jax.device_put(src._data)
                            if o.context == src.context else
                            jax.device_put(src._data,
                                           o.context.jax_device()))

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference: kvstore.h
        PullRowSparse / KVStoreLocal::PullRowSparseImpl,
        kvstore_local.h:188).

        O(requested rows): gathers the rows on device.  A RowSparseNDArray
        ``out`` receives values+indices with NO dense materialization; a
        dense ``out`` gets the scatter fallback.
        """
        from .ndarray.sparse import RowSparseNDArray
        assert out is not None and row_ids is not None
        keys, outs = self._canon(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, os_, rid in zip(keys, outs, row_ids):
            src = self._store[k]
            # dedup row ids (reference: PullRowSparseImpl dedups before
            # gathering) — duplicates would double-count in the rsp view
            idx = jnp.asarray(
                np.unique(np.asarray(rid.asnumpy(), dtype=np.int64)),
                dtype=jnp.int32)
            rows = jnp.take(src._data, idx, axis=0)
            for o in os_:
                if isinstance(o, RowSparseNDArray):
                    # re-arm in place with the gathered rows (O(rows))
                    RowSparseNDArray.__init__(
                        o, NDArray(rows), NDArray(idx.astype(jnp.int64)),
                        tuple(src.shape))
                else:
                    # dense out: scatter fallback
                    o._set_data(
                        jnp.zeros_like(src._data).at[idx].set(rows))

    # -- optimizer ------------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Run optimizer inside the store (reference: kvstore.py:353
        update-on-kvstore; server-side optimizer in dist mode)."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    # -- coordination ---------------------------------------------------------
    def barrier(self):
        """Global barrier (reference: Postoffice::Barrier)."""
        from . import distributed as _dist
        _dist.barrier("mxnet_tpu_kvstore_barrier")

    def _send_command_to_servers(self, head, body):
        pass  # no server processes exist in the TPU design

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("there is no optimizer installed")
        with open(fname, 'wb') as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("there is no optimizer installed")
        with open(fname, 'rb') as fin:
            self._updater.set_states(fin.read())

    # -- internals ------------------------------------------------------------
    def _reduce(self, vs: List[NDArray]):
        """Sum the pushed copies; reduce WHERE THE DATA LIVES (reference:
        CommDevice reduces on the devices holding the data, comm.h:462).

        Values living on distinct devices are viewed as ONE device-spanning
        stacked jax.Array and summed with replicated output, so XLA emits
        an ICI all-reduce instead of gathering every copy through a single
        chip; the result then lands on the first value's device (same
        contract as the gather path) via a local no-copy shard pick.
        Same-device / mixed-placement values keep the stacked-jit sum."""
        if len(vs) == 1:
            return vs[0]._data
        datas = [v._data for v in vs]
        devs = []
        for x in datas:
            ds = getattr(x, "devices", None)
            ds = tuple(ds()) if callable(ds) else ()
            devs.append(ds[0] if len(ds) == 1 else None)
        if (None not in devs and len(set(devs)) == len(devs) > 1
                and len({d.platform for d in devs}) == 1):
            # distinct same-platform devices: all-reduce on the mesh
            # (a cpu+tpu mix can't form one mesh — gather instead)
            return self._reduce_on_mesh(datas, devs)
        uniq = {d for d in devs if d is not None}
        if len(uniq) > 1 or (None in devs and uniq):
            # mixed placement (repeated devices, cross-platform values,
            # or a sharded value beside committed ones): explicit gather
            # to the first value's device — jit refuses committed args
            # spread over devices
            target = devs[0] or next(d for d in devs if d is not None)
            datas = [jax.device_put(x, target) for x in datas]
        sig = (len(vs), vs[0].shape, str(vs[0].dtype))
        if sig not in self._sum_cache:
            self._sum_cache[sig] = jax.jit(
                lambda *xs: jnp.sum(jnp.stack(xs), axis=0)
                if len(xs) > 2 else (xs[0] + xs[1]))
        return self._sum_cache[sig](*datas)

    def _reduce_on_mesh(self, datas, devs):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        shape, dtype = datas[0].shape, datas[0].dtype
        # frozenset: the jitted sum is permutation-invariant and shards
        # are matched to mesh positions by their DEVICE, so one compiled
        # reducer serves every arrival order of the same device set
        sig = ("mesh", len(datas), shape, str(dtype),
               frozenset(d.id for d in devs))
        if sig not in self._sum_cache:
            mesh = Mesh(np.array(devs), ("kv",))
            sharded = NamedSharding(mesh, PartitionSpec("kv"))
            replicated = NamedSharding(mesh, PartitionSpec())
            fn = jax.jit(lambda x: jnp.sum(x, axis=0),
                         out_shardings=replicated)
            self._sum_cache[sig] = (sharded, fn)
        sharded, fn = self._sum_cache[sig]
        stacked = jax.make_array_from_single_device_arrays(
            (len(datas),) + tuple(shape), sharded,
            [x[None] for x in datas])
        return jax.device_put(fn(stacked), devs[0])

    @staticmethod
    def _key_int(k):
        try:
            return int(k)
        except ValueError:
            return k

    @staticmethod
    def _canon(key, value):
        single = not isinstance(key, (list, tuple))
        keys = [key] if single else list(key)
        if single:
            values = [value if isinstance(value, (list, tuple)) else [value]]
        else:
            values = [v if isinstance(v, (list, tuple)) else [v]
                      for v in value]
        return [_key(k) for k in keys], values


def create(name="local") -> KVStore:
    """reference: kvstore.py:534 create → KVStore::Create (kvstore.cc:34)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in ("local", "local_allreduce_cpu", "local_allreduce_device",
                "device", "tpu", "dist_sync", "dist_device_sync", "dist",
                "nccl"):
        return KVStore(name)
    if name == "dist_async":
        raise MXNetError(
            "kvstore 'dist_async' is not supported by the TPU design: SPMD "
            "collectives are synchronous. Use 'dist_sync' (allreduce over "
            "the global mesh) — see docs/design/kvstore.md")
    raise MXNetError(f"unknown kvstore type {name!r}")
