"""KVStore: key-value parameter synchronization.

TPU-native re-design of the reference's kvstore stack (include/mxnet/
kvstore.h:45-397; src/kvstore/kvstore_local.h, comm.h, kvstore_dist.h).
The public API (init/push/pull/row_sparse_pull/set_optimizer/rank/
num_workers/barrier) is preserved; the transport is re-imagined:

* ``local`` / ``device`` — single-process aggregation.  The reference's
  CommCPU/CommDevice reduction trees (comm.h:90,462) collapse to a jnp sum
  (XLA emits the optimal reduction; cross-device copies ride ICI when the
  values live on different chips of a mesh).
* ``tpu`` — values that are sharded jax.Arrays over a device mesh are
  reduced with a jitted psum-style sum so gradient aggregation fuses and
  rides ICI collectives (SURVEY.md §5.8 north star).
* ``dist_sync`` — multi-process: the locally-reduced value is summed
  across processes (``distributed.allreduce_sum``, a host-side gather —
  gloo on CPU test clusters, DCN on pods) and every process applies the
  identical update.  This is the *compatibility* path giving the
  reference's exact worker-visible push/pull semantics; the *performance*
  path for multi-host training is ``Module(..., mesh=...)`` where GSPMD
  fuses the gradient psum into the jitted step (docs/design/kvstore.md).
  There are no parameter-server processes (kvstore_dist_server.h is
  intentionally not ported).
* ``dist_async`` — real async parameter servers (``KVStoreDistAsync``
  below + ``kvstore_server.py``): host-side server processes apply each
  push the moment it arrives (reference kvstore_dist_server.h:405-430),
  workers push through a background channel so device compute never
  blocks on a collective.  Launch with ``tools/launch.py -n W -s S``.

Update-on-kvstore (reference: server-side optimizer, kvstore_dist_server.h
:131) is supported: ``set_optimizer`` installs an Updater that runs the
fused update on the aggregated gradient.
"""
from __future__ import annotations

import os
import pickle
import queue
import threading
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .analysis import hb as _hb
from .base import MXNetError
from .compression import RowSparsePayload
from .ndarray import NDArray
from . import optimizer as opt
from . import tracing as _tr
from . import health as _health
# canonical key coercion lives beside the wire protocol so worker-side
# and server-side updater indexing can never diverge
from .kvstore_server import _key_int as _key_int_impl


def _key(k):
    return str(k)


def _write_row_sparse_out(outs, rows, idx, full_shape):
    """Write gathered rows into out array(s): a RowSparseNDArray is
    re-armed in place with values+indices (no dense materialization), a
    dense out gets the scatter fallback.  Shared by the local store and
    the dist_async worker so the out-array semantics can't diverge."""
    import jax.numpy as jnp
    from .ndarray.sparse import RowSparseNDArray
    jidx = jnp.asarray(idx, dtype=jnp.int64)
    for o in outs:
        if isinstance(o, RowSparseNDArray):
            RowSparseNDArray.__init__(
                o, NDArray(rows), NDArray(jidx), tuple(full_shape))
        else:
            o._set_data(jnp.zeros(tuple(full_shape),
                                  rows.dtype).at[jidx].set(rows))


class KVStore:
    """Single-process store (reference: KVStoreLocal, kvstore_local.h)."""

    def __init__(self, kvtype="local"):
        self.type = kvtype
        self._store: Dict[str, NDArray] = {}
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0
        self._gcompress = None
        # jitted multi-value reducer cache keyed by (n_values, shape, dtype)
        self._sum_cache = {}

    # -- identity -----------------------------------------------------------
    @property
    def rank(self) -> int:
        return jax.process_index() if self.type.startswith(("dist", "tpu")) \
            else 0

    @property
    def num_workers(self) -> int:
        return jax.process_count() if self.type.startswith(("dist", "tpu")) \
            else 1

    # -- init ----------------------------------------------------------------
    def init(self, key, value):
        keys, values = self._canon(key, value)
        for k, vs in zip(keys, values):
            if k in self._store:
                raise MXNetError(f"duplicate init of key {k}")
            val = vs[0]._data
            if self.type.startswith("dist") and self.num_workers > 1:
                # rank 0's init value is authoritative (reference: first
                # worker init wins at the server, kvstore_dist_server.h)
                from . import distributed as _dist
                val = jnp.asarray(_dist.broadcast_from_root(np.asarray(val)))
            self._store[k] = NDArray(val)

    # -- push/pull ------------------------------------------------------------
    def push(self, key, value, priority=0):
        """Aggregate value(s) into the store; runs updater if installed
        (reference: KVStoreLocal::PushImpl, kvstore_local.h:149).

        dist types additionally sum the locally-reduced value across all
        processes (the allreduce that replaces the reference's
        server-side MergeBuf aggregation, kvstore_dist_server.h:175-198);
        every process then applies the identical update, so the store
        stays replicated-consistent with no server round trip."""
        keys, values = self._canon(key, value)
        for k, vs in zip(keys, values):
            agg = self._reduce(vs)
            if self.type.startswith("dist") and self.num_workers > 1:
                from . import distributed as _dist
                agg = jnp.asarray(_dist.allreduce_sum(np.asarray(agg)))
            if k not in self._store:
                raise MXNetError(f"push to uninitialized key {k}")
            if self._updater is not None:
                self._updater(self._key_int(k), NDArray(agg), self._store[k])
            else:
                # no updater: store holds the reduced value (reference:
                # kvstore_local.h:173 local = merged — assign, don't add)
                self._store[k]._set_data(agg)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value to out array(s)
        (reference: KVStoreLocal::PullImpl, kvstore_local.h:188)."""
        assert out is not None
        keys, outs = self._canon(key, out)
        for k, os_ in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"pull of uninitialized key {k}")
            src = self._store[k]
            for o in os_:
                o._set_data(jax.device_put(src._data)
                            if o.context == src.context else
                            jax.device_put(src._data,
                                           o.context.jax_device()))

    def assign(self, key, value):
        """Store value(s) VERBATIM, bypassing any installed updater, and
        creating missing keys.  No reference analog: this is the
        control-plane register the serving tier's weight-version counter
        rides (:mod:`mxnet_tpu.serving` — routing a version bump through
        ``push`` would hand it to the optimizer as a gradient)."""
        keys, values = self._canon(key, value)
        for k, vs in zip(keys, values):
            val = vs[0]._data
            if k in self._store:
                self._store[k]._set_data(val)
            else:
                self._store[k] = NDArray(val)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference: kvstore.h
        PullRowSparse / KVStoreLocal::PullRowSparseImpl,
        kvstore_local.h:188).

        O(requested rows): gathers the rows on device.  A RowSparseNDArray
        ``out`` receives values+indices with NO dense materialization; a
        dense ``out`` gets the scatter fallback.
        """
        assert out is not None and row_ids is not None
        keys, outs = self._canon(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        from . import membership as _mem
        for k, os_, rid in zip(keys, outs, row_ids):
            if _mem.STRIPE_SEP in k:
                # same reservation the dist stripe planner enforces:
                # a user key carrying the separator would collide with
                # striped wire keys the moment the job goes dist
                raise MXNetError(
                    f"kvstore {self.type}: key {k!r} contains the "
                    f"reserved stripe separator "
                    f"'{_mem.STRIPE_SEP}' — rename the parameter")
            if k not in self._store:
                raise MXNetError(f"pull of uninitialized key {k}")
            src = self._store[k]
            # dedup row ids (reference: PullRowSparseImpl dedups before
            # gathering) — duplicates would double-count in the rsp view
            idx = np.unique(np.asarray(rid.asnumpy(), dtype=np.int64))
            rows = jnp.take(src._data, jnp.asarray(idx, dtype=jnp.int32),
                            axis=0)
            _write_row_sparse_out(os_, rows, idx, src.shape)

    # -- optimizer ------------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Run optimizer inside the store (reference: kvstore.py:353
        update-on-kvstore; server-side optimizer in dist mode)."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """reference: kvstore.py set_gradient_compression (MXNet 0.12,
        2-bit gradient compression).  ``{'type': '2bit', 'threshold':
        t}`` or ``{'type': 'fp16'}``; supported for device/dist stores
        only, like the reference.  Compression changes the WIRE
        representation of pushes — for store types with no wire (local
        aggregation, SPMD allreduce) the setting is validated and
        recorded but has no effect; ``dist_async`` compresses each push
        payload worker-side with error feedback
        (:mod:`mxnet_tpu.compression`), and pull stays full precision."""
        from .compression import GradientCompression
        if self.type.startswith("local"):
            raise MXNetError(
                "gradient compression is not supported for kvstore type "
                f"{self.type!r} (reference: local stores don't compress)")
        self._gcompress = GradientCompression(compression_params)

    # -- coordination ---------------------------------------------------------
    def barrier(self):
        """Global barrier (reference: Postoffice::Barrier)."""
        from . import distributed as _dist
        _dist.barrier("mxnet_tpu_kvstore_barrier")

    def num_dead_nodes(self) -> int:
        """reference: kvstore.h:328 KVStore::get_num_dead_node.  SPMD /
        local stores have no partial-failure mode of their own; report
        the job-wide count (dist_async channels register theirs with
        :func:`distributed.num_dead_nodes`)."""
        from . import distributed as _dist
        return _dist.num_dead_nodes()

    def server_stats(self, rank: int = 0) -> dict:
        """The profiler snapshot of "server" ``rank`` (docs/
        OBSERVABILITY.md).  Store types with no server processes ARE
        their own server: the local process's snapshot comes back, so
        callers sweep uniformly across store types.  ``KVStoreDistAsync``
        overrides this with the real ``("stats",)`` wire op."""
        from . import profiler as _prof
        if rank != 0:
            raise MXNetError(
                f"kvstore type {self.type!r} has no server rank {rank}")
        return _prof.snapshot()

    def _send_command_to_servers(self, head, body):
        pass  # sync/allreduce types have no server processes
        # (KVStoreDistAsync overrides this with a real send)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("there is no optimizer installed")
        with open(fname, 'wb') as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("there is no optimizer installed")
        with open(fname, 'rb') as fin:
            self._updater.set_states(fin.read())

    # -- internals ------------------------------------------------------------
    def _reduce(self, vs: List[NDArray]):
        """Sum the pushed copies; reduce WHERE THE DATA LIVES (reference:
        CommDevice reduces on the devices holding the data, comm.h:462).

        Values living on distinct devices are viewed as ONE device-spanning
        stacked jax.Array and summed with replicated output, so XLA emits
        an ICI all-reduce instead of gathering every copy through a single
        chip; the result then lands on the first value's device (same
        contract as the gather path) via a local no-copy shard pick.
        Same-device / mixed-placement values keep the stacked-jit sum."""
        if len(vs) == 1:
            return vs[0]._data
        datas = [v._data for v in vs]
        devs = []
        for x in datas:
            ds = getattr(x, "devices", None)
            ds = tuple(ds()) if callable(ds) else ()
            devs.append(ds[0] if len(ds) == 1 else None)
        if (None not in devs and len(set(devs)) == len(devs) > 1
                and len({d.platform for d in devs}) == 1):
            # distinct same-platform devices: all-reduce on the mesh
            # (a cpu+tpu mix can't form one mesh — gather instead)
            return self._reduce_on_mesh(datas, devs)
        uniq = {d for d in devs if d is not None}
        if len(uniq) > 1 or (None in devs and uniq):
            # mixed placement (repeated devices, cross-platform values,
            # or a sharded value beside committed ones): explicit gather
            # to the first value's device — jit refuses committed args
            # spread over devices
            target = devs[0] or next(d for d in devs if d is not None)
            datas = [jax.device_put(x, target) for x in datas]
        sig = (len(vs), vs[0].shape, str(vs[0].dtype))
        if sig not in self._sum_cache:
            self._sum_cache[sig] = jax.jit(
                lambda *xs: jnp.sum(jnp.stack(xs), axis=0)
                if len(xs) > 2 else (xs[0] + xs[1]))
        return self._sum_cache[sig](*datas)

    def _reduce_on_mesh(self, datas, devs):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        shape, dtype = datas[0].shape, datas[0].dtype
        # frozenset: the jitted sum is permutation-invariant and shards
        # are matched to mesh positions by their DEVICE, so one compiled
        # reducer serves every arrival order of the same device set
        sig = ("mesh", len(datas), shape, str(dtype),
               frozenset(d.id for d in devs))
        if sig not in self._sum_cache:
            mesh = Mesh(np.array(devs), ("kv",))
            sharded = NamedSharding(mesh, PartitionSpec("kv"))
            replicated = NamedSharding(mesh, PartitionSpec())
            fn = jax.jit(lambda x: jnp.sum(x, axis=0),
                         out_shardings=replicated)
            self._sum_cache[sig] = (sharded, fn)
        sharded, fn = self._sum_cache[sig]
        stacked = jax.make_array_from_single_device_arrays(
            (len(datas),) + tuple(shape), sharded,
            [x[None] for x in datas])
        return jax.device_put(fn(stacked), devs[0])

    _key_int = staticmethod(_key_int_impl)

    @staticmethod
    def _canon(key, value):
        single = not isinstance(key, (list, tuple))
        keys = [key] if single else list(key)
        if single:
            values = [value if isinstance(value, (list, tuple)) else [value]]
        else:
            values = [v if isinstance(v, (list, tuple)) else [v]
                      for v in value]
        return [_key(k) for k in keys], values


class _ServerConn:
    """Ordered async channel to one parameter server.

    Operations enqueue; one IO thread per server runs a SLIDING-WINDOW
    pipeline: up to ``MXNET_KVSTORE_WINDOW`` (default 8) envelopes are
    in flight at once, acks are consumed from the head of a FIFO of
    pending slots.  A ``push`` therefore returns before the server
    applies it (the async overlap the reference gets by running
    ``ZPush`` inside an engine async op, kvstore_dist.h:53-80) and a
    burst of N requests costs ~1 RTT instead of N — the pipelined
    ZPush/ZPull behavior of ps-lite, where the old loop was
    stop-and-wait.  Per-server FIFO ordering is preserved exactly
    (requests are sent in enqueue order, acks arrive in that order on
    one TCP stream), so a later ``pull`` still observes every prior
    push from THIS worker; ``MXNET_KVSTORE_WINDOW=1`` degrades to the
    old send-one-await-one behavior bit for bit.

    **Fault tolerance** (reference: ps-lite resender + the server-
    recovery mode, kvstore_dist.h:55).  Every request travels in an
    envelope ``("req", (rank, nonce), seq, msg)``; on transport death
    the IO thread reconnects with capped exponential backoff
    (``MXNET_KVSTORE_RETRY_*``) and REPLAYS the ENTIRE unacked window
    in seq order — the server's per-client dedup window acks
    already-applied replays idempotently, so a connection killed with
    k envelopes in flight still applies each exactly once.  Retries
    are bounded: exhausting ``MXNET_KVSTORE_RETRY_MAX`` reconnect
    attempts surfaces the original transport error as the permanent
    channel failure, failing every in-flight request.

    **Liveness.**  A low-rate heartbeat thread pings the server on its
    OWN socket (the data channel legitimately blocks unboundedly in
    barrier waits); ``is_dead()`` reports silence past
    ``MXNET_KVSTORE_HEARTBEAT_TIMEOUT`` and feeds ``num_dead_nodes()``.
    """

    def __init__(self, uri, connect_timeout=60.0, window=None, rank=None,
                 byte_kinds=("sent", "recv")):
        import collections
        import socket as _socket
        import time
        import uuid
        self._uri = uri
        host, port = uri.rsplit(":", 1)
        self._addr = (host, int(port))
        # ``rank`` override: in-process multi-worker tests (and the
        # hierarchical tier's follower channels) run several stores of
        # DIFFERENT ranks in one process, where the env var can only
        # name one.  ``byte_kinds`` is the (send, recv) counter family
        # pair — mesh channels count under "ici_*" (kvstore_server
        # _send_msg byte_kind), the wire keeps the classic kinds.
        self._rank = int(os.environ.get("DMLC_WORKER_ID", "0")
                         if rank is None else rank)
        self._byte_kinds = tuple(byte_kinds)
        # channel identity: (worker_rank, nonce).  The nonce survives
        # reconnects (so replays dedup) but differs between channel
        # INSTANCES — two clients of the same rank (relaunch, tests)
        # must never collide in the server's dedup window.
        self._client_id = (self._rank, uuid.uuid4().hex[:16])
        # control-plane counter pair for hellos/heartbeats on this
        # channel family: wire channels use "control*", mesh channels
        # stay inside the ici_ family ("ici_control*")
        self._ctrl_kinds = (("control", "control_recv")
                            if self._byte_kinds[0] == "sent"
                            else ("ici_control", "ici_control_recv"))
        self._next_seq = 0
        from .base import env as _env
        self._retry_max = int(_env("MXNET_KVSTORE_RETRY_MAX", 8))
        self._retry_initial = float(
            _env("MXNET_KVSTORE_RETRY_INITIAL_MS", 50)) / 1000.0
        self._retry_cap = float(
            _env("MXNET_KVSTORE_RETRY_MAX_MS", 2000)) / 1000.0
        self._retry_backoff = float(_env("MXNET_KVSTORE_RETRY_BACKOFF", 2.0))
        self._retry_attempts = 0
        self._closing = threading.Event()
        self._last_transport_err = None
        # same-host shm lane (mxnet_tpu/shmlane.py): set up AFTER the
        # channel exists via setup_shm_lane() — None means plain TCP.
        # Written on the caller's thread before any request that could
        # ride it is enqueued (the queue put is the happens-before
        # edge); read only by the IO thread afterwards.
        self._shm = None
        self._shm_stall_s = float(_env("MXNET_KVSTORE_SHM_STALL_S", 5.0))
        self._shm_sent_at = None
        self._sock = self._dial(connect_timeout)
        self._q = queue.Queue()
        self._err = None
        self._dead = False   # IO thread crashed (set after _err; see _io_loop)
        # sliding window: entries are [envelope, pending, replayed] in
        # seq order; head = oldest unacked.  ``window`` overrides the
        # env (the serving client opens wide pipelines per connection
        # without re-configuring the training job's kvstore channels).
        self._window = max(1, int(window if window is not None
                                  else _env("MXNET_KVSTORE_WINDOW", 8)))
        self._inflight = collections.deque()
        # wakeup pair: lets the IO thread wait on "ack readable" AND
        # "new request enqueued" at once (select) without polling
        self._wake_r, self._wake_w = _socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._thread = threading.Thread(target=self._io_loop, daemon=True)
        self._thread.start()
        self._hb_interval = float(
            _env("MXNET_KVSTORE_HEARTBEAT_INTERVAL", 5.0))
        self._hb_timeout = float(
            _env("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", 15.0))
        self._hb_last_ack = time.monotonic()
        self._hb_thread = None
        if self._hb_interval > 0:
            self._hb_thread = threading.Thread(target=self._hb_loop,
                                               daemon=True)
            self._hb_thread.start()

    def _dial(self, connect_timeout):
        import socket
        import time
        from . import faultinject
        from . import wirecodec as _codec
        from .kvstore_server import _set_nodelay, _send_msg, _recv_msg
        deadline = time.monotonic() + connect_timeout
        while True:
            try:
                faultinject.client_connect(self._uri)
                sock = socket.create_connection(self._addr, timeout=60)
                # the connect timeout must NOT linger as a recv timeout:
                # a barrier reply legitimately blocks until every worker
                # arrives (unbounded); transport death still surfaces as
                # ECONNRESET/EOF when the server process dies
                sock.settimeout(None)
                _set_nodelay(sock)
                # one synchronous codec hello before pipelined traffic:
                # hot frames go binary when the peer speaks v2, old
                # peers answer err/None and the socket stays pickle
                _codec.client_hello(sock, _send_msg, _recv_msg,
                                    byte_kinds=self._ctrl_kinds)
                return sock
            except (ConnectionRefusedError, OSError):
                # the server process is still importing/binding — workers
                # and servers start simultaneously (tools/launch.py)
                if time.monotonic() >= deadline:
                    raise MXNetError(
                        f"could not reach kvstore server at {self._uri} "
                        f"within {connect_timeout}s")
                time.sleep(0.2)

    def _enqueue(self, item):
        """Queue a request and poke the IO thread's select()."""
        self._q.put(item)
        if self._dead:
            # the IO thread crashed between the caller's _err check and
            # the put: nobody will ever dequeue this item — fail it here
            # (_dead is set after _err and before the crash handler's
            # drain, so seeing it guarantees _err is readable and that a
            # put the handler missed is ours to fail)
            self._drain_queue_failing(self._err)
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, OSError):
            pass  # buffer full / closed: the thread is awake regardless

    def _io_loop(self):
        """Thread entry: the pump with crash propagation.  Transport
        faults have their own recovery path (_recover_or_fail), but an
        UNEXPECTED crash in the pump logic itself used to kill the IO
        thread silently — every queued request's ``pending.done`` then
        never fires and callers block forever.  Park the failure as the
        channel poison instead (the sticky-error pattern): in-flight
        and queued requests fail with the cause, later enqueues raise
        up front (``_err`` check in request())."""
        try:
            self._io_pump()
        except Exception as exc:  # noqa: BLE001 — crossing a thread
            err = MXNetError(
                f"kvstore channel to {self._uri}: IO thread crashed: "
                f"{type(exc).__name__}: {exc}")
            err.__cause__ = exc
            self._channel_failed(err)   # sets _err, fails the window
            # _dead AFTER _err, BEFORE the drain: an enqueue that slips
            # past request()'s _err precheck either lands before this
            # drain (drained here) or puts after it — and then its own
            # _enqueue post-check observes _dead=True and self-drains.
            # Checking thread.is_alive() instead would leave a window
            # (drain done, thread not yet exited).
            self._dead = True
            self._drain_queue_failing(err)

    def _drain_queue_failing(self, err):
        """Fail every request still sitting in the enqueue queue (the
        window drain in _channel_failed only covers in-flight ones)."""
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                self._fail_pending(item[1], err)

    def _io_pump(self):
        """The sliding-window pump.  Fill the window from the queue,
        then wait for whichever comes first: an ack (completes the head
        slot) or a wakeup byte (new work while acks are outstanding).
        With MXNET_KVSTORE_WINDOW=1 this is exactly the old
        send-one-await-one loop."""
        import select
        stopping = False
        while True:
            while not stopping and len(self._inflight) < self._window:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    if self._inflight:
                        break
                    item = self._q.get()   # idle: block until work/close
                if item is None:
                    stopping = True
                    break
                self._send_request(item)
                self._drain_ready_acks(select)
            if not self._inflight:
                if stopping:
                    return
                continue
            if self._shm is not None:
                self._await_ack_shm(select)
                continue
            try:
                ready, _, _ = select.select(
                    [self._sock, self._wake_r], [], [])
            except (OSError, ValueError, TypeError):
                # socket torn down under us (close() path): surface it
                # through the ordinary recv-failure machinery
                ready = [self._sock]
            if self._wake_r in ready:
                try:
                    while self._wake_r.recv(4096):
                        pass
                except (BlockingIOError, OSError):
                    pass
            if self._sock in ready:
                self._recv_ack()

    def _drain_ready_acks(self, select):
        """Between sends of a burst, consume any acks already on the
        wire (zero-timeout poll).  Frees window slots early and keeps
        the peer's (tiny) ack sends flowing while we stream — blocking
        sendall with a peer that is also mid-sendall is the one mutual-
        stall shape pipelining could otherwise create.  NOTE the public
        ops can't reach that shape anyway (pull/row_sparse_pull await
        their large replies before returning, so big replies never
        overlap big sends on one conn); only a caller hand-pipelining
        ``request()`` of large pulls between large pushes could."""
        while self._inflight and self._sock is not None:
            try:
                ready, _, _ = select.select([self._sock], [], [], 0)
            except (OSError, ValueError, TypeError):
                return
            if not ready:
                return
            self._recv_ack()

    def _send_request(self, item):
        """Assign the next seq, enter the window, send.  The entry joins
        the window BEFORE the send so a mid-send transport fault replays
        it with its original (client_id, seq)."""
        from .kvstore_server import _send_msg
        from . import faultinject
        msg, pending, tctx = item
        if self._err is not None and self._sock is None:
            # hard transport poison: the channel is gone for good — fail
            # queued work instead of sending into nothing.  An
            # APPLICATION-error poison (server said "err" to a fire-and-
            # forget push; the socket is healthy) must NOT drop
            # already-queued requests: they keep flowing, exactly like
            # the pre-window serial loop ("a lost gradient must not
            # pass silently" — only NEW enqueues are refused).
            self._fail_pending(pending, self._err)
            return
        if tctx is not None:
            # trace propagation (mxnet_tpu.tracing): the optional 5th
            # element carries (trace_id, parent span_id, send epoch-us)
            # captured at ENQUEUE time on the caller's thread — the
            # server opens a child span of the worker-side call.  The
            # stamped envelope lives in the window, so a reconnect
            # REPLAYS the same trace field: retries annotate the
            # original trace instead of starting a new one.  With
            # MXNET_TRACE=0 the envelope stays the classic 4-tuple —
            # zero added wire bytes (pinned by tests/test_tracing.py).
            envelope = ("req", self._client_id, self._next_seq, msg,
                        (tctx[0], tctx[1], _tr.now_us()))
        else:
            envelope = ("req", self._client_id, self._next_seq, msg)
        self._next_seq += 1
        self._inflight.append([envelope, pending, False])
        lane = self._shm
        if lane is not None and lane.dead():
            # peer marked it dead (leader teardown) — quiet drop, the
            # socket still works
            self._shm_drop()
            lane = None
        if lane is not None:
            from . import wirecodec as _codec
            try:
                sent = lane.send_request(
                    envelope, binary_ok=_codec.sock_binary(self._sock))
            except MXNetError:
                sent = False   # ring corrupt: fall through to TCP and
                #                let the next wait cycle kill the lane
            if sent:
                # one memcpy into the ring, zero socket syscalls; the
                # stall watchdog clock starts now.  fi kill hooks stay
                # socket-only — the lane has its own fault point
                # (MXNET_FI_SHM_WEDGE_AFTER).
                import time as _time
                self._shm_sent_at = _time.monotonic()
                return
        try:
            if self._sock is None:
                raise ConnectionError("channel has no connection")
            _send_msg(self._sock, envelope, fi_role="client",
                      byte_kind=self._byte_kinds[0])
            faultinject.client_window(self._sock, len(self._inflight))
        except Exception as exc:  # noqa: BLE001 — transport fault
            self._recover_or_fail(exc)

    def _await_ack_shm(self, select):
        """The shm-lane flavor of the ack wait: poll the reply ring
        (payload acks ride back the same lane) TOGETHER with the
        socket (server-side fallback replies — e.g. a frame too big
        for the ring went over TCP and so does its ack) and the wakeup
        pair.  Adaptive poll interval: sub-millisecond while hot (the
        in-host RTT this lane exists for), backing off to 2 ms so an
        idle wait doesn't spin a core.  The stall watchdog rides the
        same loop: a request sitting unconsumed in the ring past
        MXNET_KVSTORE_SHM_STALL_S means the leader stopped draining —
        mark the lane dead and fail over through the ordinary
        reconnect-and-replay path (closing the old socket is what
        makes a racing leader reply harmless: it dies with the
        connection, and the replayed envelope is deduped)."""
        import time
        lane = self._shm
        poll = 0.0002
        while self._inflight:
            try:
                reply = lane.recv_reply()
            except MXNetError as exc:
                self._shm_fault(f"reply ring corrupt: {exc}")
                return
            if reply is not None:
                self._ack_obj(reply)
                return
            if lane.dead():
                self._shm_fault("peer marked the lane dead")
                return
            try:
                ready, _, _ = select.select(
                    [self._sock, self._wake_r], [], [], poll)
            except (OSError, ValueError, TypeError):
                ready = [self._sock]
            if self._wake_r in ready:
                try:
                    while self._wake_r.recv(4096):
                        pass
                except (BlockingIOError, OSError):
                    pass
                return   # new work enqueued — go fill the window
            if self._sock in ready:
                self._recv_ack()
                return
            if (self._shm_sent_at is not None
                    and lane.request_backlog() > 0
                    and time.monotonic() - self._shm_sent_at
                    > self._shm_stall_s
                    and lane.drain_stalled(self._shm_stall_s)):
                self._shm_fault(
                    f"leader stopped draining the request ring for "
                    f">{self._shm_stall_s}s (MXNET_KVSTORE_SHM_STALL_S)")
                return
            poll = min(poll * 2, 0.002)

    def _shm_drop(self, record=False):
        """Forget the lane (quietly or loudly) — mark dead so the peer
        stops serving it, unlink the segment (our mapping and any
        still-open peer mapping stay valid until their own close)."""
        lane, self._shm = self._shm, None
        self._shm_sent_at = None
        if lane is None:
            return
        try:
            lane.mark_dead()
            lane.destroy()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        if record:
            from . import profiler as _prof
            _prof.record_channel_event("kvstore.shm_fallback")

    def _shm_fault(self, why):
        """Lane failure → the transport-fault path the channel already
        survives: drop the lane, then reconnect-and-replay over TCP
        (the leader's per-client dedup keeps the replayed window
        exactly-once; the dead old socket swallows any reply the
        leader raced out)."""
        self._shm_drop(record=True)
        _health.note("shm.fallback", uri=self._uri, why=str(why))
        self._recover_or_fail(
            ConnectionError(f"shm lane to {self._uri}: {why}"))

    def setup_shm_lane(self):
        """Negotiate the same-host shared-memory lane for this channel
        (hierarchical-tier followers call it right after dialing,
        before any mesh traffic).  Window-1 channels only — strict
        request/reply alternation is what lets oversized frames ride
        TCP per-round with no reordering.  Returns True when the lane
        is live; every failure (knob off, remote host, segment
        creation failure, old/cross-host leader erring the hello)
        quietly keeps the channel on TCP."""
        from . import profiler as _prof
        from . import shmlane
        if self._window != 1 or not shmlane.client_enabled(self._addr[0]):
            return False
        try:
            lane = shmlane.ShmLane.create()
        except Exception:  # noqa: BLE001 — no /dev/shm, quota, ...
            return False
        try:
            ver = _await(self.request(("shm_hello", lane.name)))
        except MXNetError:
            lane.destroy()
            return False
        if not ver:
            lane.destroy()
            return False
        self._shm = lane
        _prof.record_channel_event("kvstore.shm_lane")
        return True

    def _recv_ack(self):
        """Consume ONE ack for the head of the window (acks arrive in
        seq order on the single TCP stream)."""
        from .kvstore_server import _recv_msg
        try:
            reply = _recv_msg(self._sock, fi_role="client",
                              byte_kind=self._byte_kinds[1])
        except Exception as exc:  # noqa: BLE001 — transport fault
            self._recover_or_fail(exc)
            return
        self._ack_obj(reply)

    def _ack_obj(self, reply):
        """Complete the head-of-window slot with ``reply`` — shared by
        the socket and shm-lane receive paths (the ring pops whole
        decoded frames, so both land here with the same shapes)."""
        from . import profiler as _prof
        # a complete round trip proves the transport healthy again
        self._retry_attempts = 0
        self._shm_sent_at = None
        envelope, pending, replayed = self._inflight.popleft()
        if replayed:
            _prof.record_channel_event("kvstore.replay_acked")
        status, payload = reply
        if status != "ok":
            # application error: the reply was fully read, the socket
            # is healthy — fail THIS op only.  A failed fire-and-
            # forget push has no waiter, so it surfaces on the next
            # call instead (a lost gradient must not pass silently).
            err = MXNetError(f"kvstore server error: {payload}")
            if pending is not None:
                pending.error = err
            else:
                self._err = err
        elif pending is not None:
            pending.value = payload
        if pending is not None:
            pending.done.set()

    def _recover_or_fail(self, exc):
        """Transport fault: reconnect and replay the whole unacked
        window, or — once retries are exhausted (or during close) —
        poison the channel and fail every in-flight request."""
        try:
            if self._closing.is_set():
                raise exc
            self._last_transport_err = exc
            self._reconnect(exc)   # raises once retries are exhausted
            self._replay_window()
        except Exception as hard:  # noqa: BLE001 — poison for good
            self._channel_failed(hard)

    def _replay_window(self):
        """Resend every unacked envelope in seq order on the fresh
        connection.  The server's per-client dedup window acks the
        already-applied ones idempotently; a fault mid-replay reconnects
        and restarts the whole window (same idempotence argument)."""
        from .kvstore_server import _send_msg
        from . import profiler as _prof
        while True:
            try:
                for entry in self._inflight:
                    _prof.record_channel_event("kvstore.replay")
                    entry[2] = True
                    _send_msg(self._sock, entry[0], fi_role="client",
                              byte_kind=self._byte_kinds[0])
                return
            except Exception as exc:  # noqa: BLE001 — fault mid-replay
                if self._closing.is_set():
                    raise
                self._last_transport_err = exc
                self._reconnect(exc)   # raises once retries exhausted

    def _channel_failed(self, exc):
        """Permanent failure: record the poison, fail the whole window.
        The flight recorder marks it too (CRITICAL while outstanding)
        and dumps a crash bundle — a hard-failed channel is exactly the
        evidence a postmortem needs from a survivor."""
        self._err = exc
        while self._inflight:
            _envelope, pending, _replayed = self._inflight.popleft()
            self._fail_pending(pending, exc)
        if not self._closing.is_set():
            _health.note_channel_poison(self._uri)

    @staticmethod
    def _fail_pending(pending, exc):
        if pending is not None:
            pending.error = exc
            pending.done.set()

    def _reconnect(self, cause):
        """Re-establish the data socket with capped exponential backoff.
        ``_retry_attempts`` persists across calls and only resets on a
        successful round trip, so a flapping server cannot stretch one
        failure episode past MXNET_KVSTORE_RETRY_MAX total attempts."""
        import socket
        from . import faultinject
        from . import profiler as _prof
        from . import wirecodec as _codec
        from .kvstore_server import _set_nodelay, _send_msg, _recv_msg
        try:
            self._sock.close()
        except (OSError, AttributeError):
            pass
        self._sock = None
        # any reconnect invalidates the shm lane: the leader's per-
        # connection attach dies with the old socket, so a fresh
        # connection runs plain TCP (rare path — lanes only die with
        # their transport or via the stall watchdog)
        if self._shm is not None:
            self._shm_drop(record=True)
        last = cause
        while True:
            if self._retry_attempts >= self._retry_max:
                _prof.record_channel_event("kvstore.hard_fail")
                raise MXNetError(
                    f"kvstore server channel to {self._uri} died "
                    f"({cause!r}) and could not be re-established after "
                    f"{self._retry_max} reconnect attempts (last error: "
                    f"{last!r}); tune MXNET_KVSTORE_RETRY_MAX / "
                    f"MXNET_KVSTORE_RETRY_INITIAL_MS / "
                    f"MXNET_KVSTORE_RETRY_MAX_MS") from cause
            self._retry_attempts += 1
            _prof.record_channel_event("kvstore.retry")
            delay = self._retry_initial * (
                self._retry_backoff ** (self._retry_attempts - 1))
            if self._closing.wait(min(delay, self._retry_cap)):
                raise MXNetError(
                    f"kvstore channel to {self._uri} closed during "
                    f"reconnect") from cause
            try:
                faultinject.client_connect(self._uri)
                sock = socket.create_connection(self._addr, timeout=60)
                sock.settimeout(None)
                _set_nodelay(sock)
                # re-negotiate BEFORE the window replay: the fresh
                # socket starts un-negotiated, and replayed envelopes
                # must ride whatever codec this round of hello agrees
                _codec.client_hello(sock, _send_msg, _recv_msg,
                                    byte_kinds=self._ctrl_kinds)
                self._sock = sock
                _prof.record_channel_event("kvstore.reconnect")
                return
            except (ConnectionRefusedError, OSError) as exc:
                last = exc
                continue

    # -- liveness ------------------------------------------------------------
    def _hb_loop(self):
        import socket
        import time
        from . import wirecodec as _codec
        from .kvstore_server import _send_msg, _recv_msg
        from . import profiler as _prof
        sock = None
        while not self._closing.is_set():
            try:
                if sock is None:
                    sock = socket.create_connection(
                        self._addr, timeout=self._hb_timeout)
                    sock.settimeout(self._hb_timeout)
                    # hello the liveness socket too: ping acks are the
                    # last pickled frames otherwise, and the steady-
                    # state pin is pickle_bytes == 0 across the job
                    _codec.client_hello(sock, _send_msg, _recv_msg,
                                        byte_kinds=self._ctrl_kinds)
                _send_msg(sock, ("ping", self._rank),
                          byte_kind=self._ctrl_kinds[0])
                status, _payload = _recv_msg(
                    sock, byte_kind=self._ctrl_kinds[1])
                if status == "ok":
                    self._hb_last_ack = time.monotonic()
                    _prof.record_channel_event("kvstore.heartbeat")
            except Exception:  # noqa: BLE001 — the miss IS the signal
                _prof.record_channel_event("kvstore.heartbeat_miss")
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
            self._closing.wait(self._hb_interval)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def is_dead(self) -> bool:
        """True when the server has not acked a heartbeat within
        MXNET_KVSTORE_HEARTBEAT_TIMEOUT.  Barrier waits on the data
        channel stay unbounded by design; SILENCE is what this
        detects."""
        import time
        if self._hb_thread is None or self._closing.is_set():
            return False
        return (time.monotonic() - self._hb_last_ack) > self._hb_timeout

    def request(self, msg):
        """Enqueue and return the :class:`_Pending` reply handle — lets a
        caller pipeline many requests before waiting on any."""
        if self._err is not None:
            raise MXNetError(f"kvstore server channel failed: {self._err}")
        pending = _Pending()
        self._enqueue((msg, pending,
                       _tr.current_ctx() if _tr.enabled() else None))
        return pending

    def submit(self, msg, wait=False):
        """Enqueue; with wait=True block for (and return) the reply."""
        if not wait:
            if self._err is not None:
                raise MXNetError(
                    f"kvstore server channel failed: {self._err}")
            self._enqueue((msg, None,
                           _tr.current_ctx() if _tr.enabled() else None))
            return None
        return _await(self.request(msg))

    def flush(self):
        """Return once every previously-enqueued op has been acked by the
        server (FIFO: a synchronous no-op command drains the queue).
        kSyncMode is the no-op of the async server (kvstore_server.py)."""
        from .kvstore_server import K_SYNC_MODE
        self.submit(("command", K_SYNC_MODE, None), wait=True)

    def close(self, join_timeout=10.0, retry=True):
        """Drain, stop the IO + heartbeat threads, close the socket.

        ``retry=False`` skips reconnect attempts during the final drain —
        the caller KNOWS the server is gone (it just sent kStopServer),
        so backing off against a deliberately stopped server only delays
        teardown."""
        if not retry:
            self._closing.set()   # recovery raises instead of reconnecting
        # drain before closing: a still-queued fire-and-forget push must
        # reach the server, not die with the socket ("a lost gradient
        # must not pass silently")
        try:
            self.flush()
        except MXNetError:
            pass  # channel already dead — nothing left to save
        self._closing.set()       # aborts any in-flight backoff sleep
        self._enqueue(None)
        self._thread.join(timeout=join_timeout)
        if self._thread.is_alive():
            # a silent leak here hid every wedged-channel teardown; name
            # the channel and its last known failure instead
            import warnings
            last = self._err or self._last_transport_err
            warnings.warn(
                f"kvstore channel to {self._uri}: IO thread did not stop "
                f"within {join_timeout:.0f}s — likely blocked awaiting a "
                f"server reply (last channel error: {last!r}); leaking "
                f"the daemon thread", RuntimeWarning, stacklevel=2)
        try:
            self._sock.close()
        except (OSError, AttributeError):
            pass
        # the IO thread is down (or leaked) — tear the lane off last so
        # the final flush above could still ride it
        self._shm_drop()
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        # poison the channel for any LATER caller: with the IO thread
        # gone, an enqueue after close would sit in the queue forever —
        # request()'s _err precheck must fail fast instead.  This bit
        # an observability sweep for real: cluster_stats() reaching a
        # closed-but-not-yet-collected store hung the whole sweep.
        if self._err is None:
            self._err = MXNetError(
                f"kvstore channel to {self._uri} is closed")
        self._dead = True
        self._drain_queue_failing(self._err)
        # a deliberately-closed channel is not an outstanding failure:
        # its poison (if any) stops contributing CRITICAL
        _health.clear_channel_poison(self._uri)

    def abort(self, join_timeout=5.0):
        """Abortive close for a channel the caller KNOWS is gray-failed
        (the peer accepts and heartbeats but stopped replying).  A
        flushing ``close()`` would wait on acks that will never come —
        and because acks are consumed strictly FIFO against the window,
        one swallowed reply misaligns every later ack on this stream,
        so the connection is unusable even if the peer recovers.  Fail
        everything in flight NOW and tear the socket down; the caller
        re-dials a fresh channel if it still wants this peer."""
        self._closing.set()
        if self._err is None:
            self._err = MXNetError(
                f"kvstore channel to {self._uri} aborted: peer stopped "
                f"replying (gray failure) — in-flight window failed")
        try:
            self._sock.close()      # wakes the IO thread mid-select
        except (OSError, AttributeError):
            pass
        self.close(join_timeout=join_timeout, retry=False)


class _Pending:
    """Reply rendezvous for one in-flight request."""

    __slots__ = ("done", "value", "error")

    def __init__(self):
        self.done = threading.Event()
        self.value = None
        self.error = None


def _await(pending):
    pending.done.wait()
    if pending.error is not None:
        raise MXNetError(f"kvstore server request failed: {pending.error}")
    return pending.value


class _WireHandle:
    """The shared timed-wait shell of the pull handles: idempotent,
    thread-safe ``wait()`` (any thread — the hierarchy tier's
    mesh-collect server waits the leader's handles concurrently with
    the fused driver) feeding the two wire-overlap clocks
    (profiler.record_wire_wait / record_wire_round): the time spent
    BLOCKED inside ``wait()`` is the exposed wire, the
    enqueue->resolved span is the full round — their ratio is the
    overlap fraction the fused-dist driver is regression-gated on.
    Subclasses implement ``_resolve() -> {key: np.ndarray}`` and
    ``_nkeys()``; ``_span_args`` tags the spans."""

    _span_args = None

    def __init__(self):
        import time
        self._t0 = time.monotonic()
        # the enqueue site's span context anchors the ROUND span: the
        # full enqueue->resolved interval crosses threads/chunks, so it
        # cannot ride the thread-local stack
        self._t0_ns = time.monotonic_ns() if _tr.enabled() else 0
        self._ctx = _tr.current_ctx() if _tr.enabled() else None
        self._result = None
        self._lock = threading.Lock()

    def wait(self):
        with self._lock:
            if self._result is not None:
                return self._result
            import time
            from . import profiler as _prof
            t_wait = time.monotonic()
            sp = _tr.span_begin("kv.wire_wait", cat="wire",
                                args=self._span_args)
            # registered with the health watchdog: a wire wait parked
            # past MXNET_HEALTH_WIRE_STALL_S with its round never
            # resolving trips a typed wire_stall event
            # (docs/OBSERVABILITY.md health section)
            wtok = _health.wait_begin("kv.wire_wait")
            try:
                # analysis: allow(blocking-under-lock): the handle lock's CONTRACT is serializing waiters — every wait() caller expects to park until the wire round resolves, and no other lock ever nests inside it
                vals = self._resolve()
            finally:
                # end even when a channel failure raises out of the
                # resolve: a leaked open span would stay on the
                # thread-local stack and mis-parent every later span
                # on this thread
                _tr.span_end(sp, args={"keys": self._nkeys()})
                _health.wait_end(wtok)
            t1 = time.monotonic()
            _prof.record_wire_wait(t1 - t_wait)
            _prof.record_wire_round(t1 - self._t0)
            if self._t0_ns:
                # the overlap the fused driver buys becomes VISIBLE:
                # the round span (enqueue->resolved) sits over the
                # wire_wait span (the exposed residue) on the timeline
                args = {"keys": self._nkeys()}
                if self._span_args:
                    args.update(self._span_args)
                _tr.add_span("kv.wire_round", self._t0_ns,
                             time.monotonic_ns(), cat="wire",
                             ctx=self._ctx, args=args)
            self._result = vals
            return vals


class _PullHandle(_WireHandle):
    """One in-flight batched pull (:meth:`KVStoreDistAsync.pull_async`):
    ``wait()`` blocks for every reply, reassembles stripes, syncs the
    elastic pull cache exactly like a blocking :meth:`pull`, and
    returns ``{key: np.ndarray}``.

    **Elastic replan** (the fused×elastic composition): entries carry
    each key's full shape and per-stripe row spans, so when a pending
    stripe dies with its server mid-flight, ``wait()`` repairs the
    roster (``KVStoreDistAsync._elastic_repair_impl``) and re-issues
    ONLY the unserved tail under the new stripe layout — stripes whose
    row span survived the bump keep their already-received values, the
    rest re-request from the new owners — then re-awaits.  Cache and
    clock bookkeeping stay exact: one ``_cache_value`` per key with the
    final assembled value (its absorb mark advanced when the replan
    re-issued against a log that had grown), one wire_wait/wire_round
    sample per handle.  Entries are ``{key, shape, mark, parts: [[lo,
    hi, wire_key, pending, value]]}`` with exactly one of
    pending/value set per part."""

    def __init__(self, kv, entries):
        super().__init__()
        self._kv = kv
        self._entries = _hb.track(entries, "kvstore._PullHandle.entries")

    def _nkeys(self):
        return len(self._entries)

    def _resolve(self):
        """Await every part; on a channel failure under
        MXNET_KVSTORE_ELASTIC, repair the roster and replan the
        unserved tail against the new stripe layout (bounded retries —
        the same budget as ``_elastic_attempt``)."""
        kv = self._kv
        attempts = 0
        while True:
            last_err = None
            for e in self._entries:
                for part in e["parts"]:
                    if part[4] is not None:
                        continue
                    if part[3] is None:
                        # re-issue itself failed last replan: the part
                        # is still unserved — keep repairing
                        last_err = last_err or MXNetError(
                            f"pull of {part[2]!r} could not be "
                            "re-issued after the roster repair")
                        continue
                    try:
                        part[4] = np.asarray(_await(part[3]))
                        part[3] = None
                    except MXNetError as exc:
                        part[3] = None
                        last_err = exc
            if last_err is None:
                break
            attempts += 1
            if not getattr(kv, "_elastic", False) or attempts > 2:
                raise last_err
            # one kv.repair span covers the roster repair AND the
            # replan instants it triggers, so the merged timeline shows
            # "this in-flight pull rode a roster bump" in one place
            with _tr.span("kv.repair", cat="elastic",
                          args={"replan": True}):
                try:
                    kv._elastic_repair_impl()
                except MXNetError:
                    pass   # re-issue below may still reach survivors
                self._replan()
        out = {}
        for e in self._entries:
            parts = sorted(e["parts"], key=lambda p: p[0])
            if len(parts) == 1:
                val = parts[0][4]
            else:
                val = np.concatenate([p[4] for p in parts], axis=0)
            # absorb only the pushes this pull OBSERVED (its enqueue
            # mark): the fused driver resolves handles chunks later,
            # with newer pushes in flight that must stay in the
            # elastic re-push log
            kv._cache_value(e["key"], val, mark=e.get("mark"))
            out[e["key"]] = val
        return out

    def _replan(self):
        """Re-derive the stripe layout of every key with unserved parts
        and re-issue exactly those — a part whose (lo, hi) row span is
        unchanged under the new plan keeps its received value (the
        'unserved tail' contract, docs/ROBUSTNESS.md).

        Mark discipline: a re-issued request is enqueued NOW — after
        the repair's handoff re-pushes and any pushes logged since the
        original enqueue (per-conn FIFO: its reply observes them all) —
        so when the log has grown past the entry's mark, the WHOLE key
        re-issues (mixing newly-observed rows with pre-push received
        spans would make the cache absorb inconsistently) and the mark
        advances to the current position.  With no interleaved pushes
        the received spans are exact and reuse is safe."""
        from . import profiler as _prof
        kv = self._kv
        for e in self._entries:
            if all(p[4] is not None for p in e["parts"]):
                continue
            k, shape = e["key"], e["shape"]
            plan = kv._stripe_plan(k, shape)
            if plan is None:
                spans = [(0, int(shape[0]) if shape else 0, k)]
            else:
                spans = [(plan[i], plan[i + 1], f"{k}@s{i}")
                         for i in range(len(plan) - 1)]
            cur_mark = kv._push_mark(k)
            if cur_mark != e.get("mark"):
                resolved = {}
                e["mark"] = cur_mark
            else:
                resolved = {(p[0], p[1]): p[4] for p in e["parts"]
                            if p[4] is not None}
            new_parts, reissued = [], 0
            for lo, hi, wk in spans:
                if (lo, hi) in resolved:
                    new_parts.append([lo, hi, wk, None, resolved[(lo, hi)]])
                    continue
                try:
                    pending = kv._owner_conn(wk).request(("pull", wk))
                except MXNetError:
                    pending = None   # still down: next attempt retries
                new_parts.append([lo, hi, wk, pending, None])
                reissued += 1
            e["parts"] = new_parts
            _prof.record_channel_event("kvstore.pull_replan")
            _tr.instant("kv.replan", cat="elastic",
                        args={"key": k, "reissued": reissued,
                              "kept": len(spans) - reissued,
                              "generation": kv._roster_gen})


class _MeshPullHandle(_WireHandle):
    """The follower half of a hierarchical pull round: one
    ``mesh_collect`` request against the host-group leader, resolved
    when the leader's own wire round for the same sequence resolves.
    Interface-compatible with :class:`_PullHandle` (``wait() -> {key:
    np.ndarray}``) and shares its timed-wait shell, so the fused
    driver's overlap accounting holds on followers too — their
    "wire" is the in-host mesh channel (spans tagged ``mesh``)."""

    _span_args = {"mesh": True}

    def __init__(self, kv, keys, pending):
        super().__init__()
        self._kv = kv
        self._keys = list(keys)
        self._pending = pending

    def _nkeys(self):
        return len(self._keys)

    def _resolve(self):
        reply = _await(self._pending)
        return {k: np.asarray(reply[k]) for k in self._keys}


class _MeshLeader:
    """The host-group leader's in-host aggregation endpoint
    (``MXNET_KVSTORE_HIERARCHY`` — the hierarchical kvstore tier).

    Followers on the same host connect through ordinary
    :class:`_ServerConn` channels (window 1: the replay window is then
    a single envelope, so the one-slot dedup below makes reconnect
    replays exactly-once) and speak three ops over the standard frame
    protocol, all bytes counted under the "ici_*" families:

    * ``("mesh_push", seq, [(key, grad), ...])`` — deposit one push
      round's gradients; the leader's ``_push_aggregated`` blocks on
      :meth:`collect_push` until every follower's round ``seq``
      arrived, reduces in-mesh and ships ONE summed push per key over
      the TCP wire.
    * ``("mesh_collect", seq, keys)`` — block until the leader's wire
      pull for sequence ``seq`` resolves (:meth:`publish_handle`
      registers it at ``pull_async`` time) and return its values: the
      weight fan-out leg.  Served directly off the leader's
      :class:`_PullHandle` (thread-safe ``wait``), so followers and
      the leader's own fused driver resolve the SAME wire round.
    * ``("command", ...)`` / ``("ping", ...)`` — flush/liveness no-ops.

    Sequences pair by SPMD lockstep: every group member executes the
    identical sequence of push/pull calls (the data-parallel contract
    the whole repo leans on), so counter ``seq`` on the follower names
    the same logical round as ``seq`` on the leader.  A member that
    falls silent trips the fan-in timeout (``MXNET_KVSTORE_MESH_FANIN_S``)
    — a loud error naming the missing round, never a silent hang (the
    wait is also health-registered, so the watchdog sees it age)."""

    def __init__(self, uri, n_followers, follower_ranks=None):
        import socket
        from .base import env as _env
        from .kvstore_server import _set_nodelay
        host, port = uri.rsplit(":", 1)
        self._uri = uri
        self._n_followers = int(n_followers)
        self._follower_ranks = (sorted(int(r) for r in follower_ranks)
                                if follower_ranks is not None else None)
        self._fanin_s = float(_env("MXNET_KVSTORE_MESH_FANIN_S", 120.0))
        self._acceptors = max(1, int(_env(
            "MXNET_KVSTORE_MESH_ACCEPTORS", 8)))
        self._listener = socket.create_server((host, int(port)))
        self._listener.settimeout(0.5)
        self._stop = threading.Event()
        self._cv = threading.Condition()
        self._pushes: Dict[int, list] = {}    # seq -> [pairs, ...]
        self._handles: Dict[int, list] = {}   # seq -> [handle, served]
        # fan-in forensics (guarded by _cv): which ranks deposited each
        # round, and when each rank was last heard from at all — the
        # timeout error names the missing ranks with last-heard ages,
        # mirroring the static barrier failure (kvstore_server).
        self._push_ranks: Dict[int, set] = {}
        self._last_heard: Dict[int, float] = {}
        # per-CLIENT envelope dedup (survives reconnects — a replay
        # arrives on a FRESH connection): cid -> (seq, reply), plus the
        # in-flight rendezvous for a replay racing the original
        self._dedup: Dict[tuple, tuple] = {}
        self._dedup_inflight: Dict[tuple, int] = {}
        self._conns: list = []
        self._pool: list = []     # _MeshAcceptor workers (accept thread
        #                           creates/assigns; each worker's conn
        #                           set is its own thread's after that)
        self._assigned = 0
        self._set_nodelay = _set_nodelay
        # analysis: allow(bare-thread): a crash closes the listener in run()'s finally — followers observe refused connects / EOF and fail their channels loudly, exactly like a dead parameter server
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- leader-side API (called from the worker's main thread) ----------
    def collect_push(self, seq):
        """Block until every follower's round ``seq`` gradients arrived;
        pop and return them (a list of ``[(key, grad), ...]``)."""
        import time as _time
        from . import profiler as _prof
        wtok = _health.wait_begin("kv.mesh_fanin")
        t0 = _time.monotonic()
        try:
            with self._cv:
                ok = self._cv.wait_for(
                    lambda: len(self._pushes.get(seq, ()))
                    >= self._n_followers or self._stop.is_set(),
                    timeout=self._fanin_s)
                if not ok or self._stop.is_set():
                    got = len(self._pushes.get(seq, ()))
                    missing, detail = self._missing_followers(seq)
                    _health.note("mesh.fanin_timeout", seq=int(seq),
                                 got=got, want=self._n_followers,
                                 missing=missing)
                    raise MXNetError(
                        f"mesh leader {self._uri}: round {seq} fan-in "
                        f"incomplete ({got} of {self._n_followers} "
                        f"followers) within "
                        f"MXNET_KVSTORE_MESH_FANIN_S={self._fanin_s}s"
                        f"{detail}")
                self._push_ranks.pop(seq, None)
                out = self._pushes.pop(seq)
            _prof.record_mesh_fanin_wait(_time.monotonic() - t0)
            return out
        finally:
            _health.wait_end(wtok)

    def _missing_followers(self, seq):
        """(missing rank list, human detail) for a fan-in timeout —
        caller holds _cv.  Degrades gracefully when the roster wasn't
        passed (direct _MeshLeader construction)."""
        import time as _time
        if self._follower_ranks is None:
            return [], ""
        present = self._push_ranks.get(seq, set())
        missing = [r for r in self._follower_ranks if r not in present]
        if not missing:
            return [], ""
        now = _time.monotonic()
        ages = "; ".join(
            "rank %s: %s" % (
                r, "never heard from" if self._last_heard.get(r) is None
                else "last heard %.1fs ago" % (now - self._last_heard[r]))
            for r in missing)
        return missing, f" — missing {ages}"

    def publish_handle(self, seq, handle):
        """Register the leader's wire pull for round ``seq`` so
        mesh_collect waiters can resolve against it."""
        with self._cv:
            self._handles[seq] = [handle, 0]
            self._cv.notify_all()

    def close(self):
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        try:
            self._listener.close()
        except OSError:
            pass
        for w in list(self._pool):
            w.poke()
        for c in list(self._conns):
            try:
                c.close()
            except OSError:
                pass
        for w in list(self._pool):
            w.thread.join(timeout=5.0)
            w.close_wake()

    # -- serve side -------------------------------------------------------
    def _run(self):
        import socket
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                self._set_nodelay(conn)
                self._conns.append(conn)
                self._assign(conn)
        finally:
            try:
                self._listener.close()
            except OSError:
                pass
            for w in list(self._pool):
                w.poke()

    def _assign(self, conn):
        """Hand a fresh connection to a pool worker (round-robin),
        growing the pool up to MXNET_KVSTORE_MESH_ACCEPTORS threads.
        Only the accept thread touches pool membership; each worker's
        connection set is thereafter its own thread's alone (adoption
        rides the worker's inbox Queue, a happens-before edge)."""
        if len(self._pool) < self._acceptors:
            w = _MeshAcceptor(self)
            self._pool.append(w)
        else:
            w = self._pool[self._assigned % len(self._pool)]
        self._assigned += 1
        w.adopt(conn)

    def _serve_pool(self, w):
        """One acceptor-pool thread: multiplex its adopted connections
        (sockets + shm lanes) with select, serving one frame per ready
        source per sweep.  mesh_collect frames that arrive before the
        leader registered the round are PARKED in ``pending`` rather
        than blocking this thread — a blocked wait here would also
        stall every other follower this thread serves, including the
        very mesh_push frames the round is waiting on."""
        import queue
        import select as _select
        conns: list = []     # _MeshConnState — this thread's alone
        # deferred mesh_collects: appended here, but drained by
        # _scan_pending against rounds the LEADER thread registers —
        # the cross-thread handoff the hb shim should see
        pending: list = _hb.track([], "kvstore._MeshAcceptor.pending")
        poll = 0.0002
        try:
            while not self._stop.is_set():
                while True:
                    try:
                        conns.append(_MeshConnState(w.inbox.get_nowait()))
                    except queue.Empty:
                        break
                lanes = any(st.lane is not None for st in conns)
                timeout = poll if (lanes or pending) else None
                try:
                    ready, _, _ = _select.select(
                        [st.sock for st in conns] + [w.wake_r],
                        [], [], timeout)
                except (OSError, ValueError):
                    for st in [s for s in list(conns)
                               if s.sock.fileno() < 0]:
                        self._drop_conn(st, conns)
                    continue
                if w.wake_r in ready:
                    try:
                        w.wake_r.recv(4096)
                    except (OSError, BlockingIOError):
                        pass
                busy = False
                rset = set(ready)
                for st in list(conns):
                    if st.sock in rset:
                        busy |= self._serve_sock(st, conns, pending)
                    if st.lane is not None:
                        busy |= self._serve_lane(st, conns, pending)
                busy |= self._scan_pending(conns, pending)
                poll = 0.0002 if busy else min(poll * 2, 0.002)
        finally:
            for st in list(conns):
                self._drop_conn(st, conns)

    def _serve_sock(self, st, conns, pending):
        from . import wirecodec as _codec
        from .kvstore_server import _recv_msg
        try:
            msg = _recv_msg(st.sock, byte_kind=st.recv_kind)
        except (ConnectionError, OSError):
            self._drop_conn(st, conns)
            return True
        reply_kind = "ici_sent"
        if msg and msg[0] == "req":
            _, cid, seq, inner = msg[:4]
            self._note_heard(cid)
            if self._defer_collect(st, pending, cid, seq, inner, False):
                return True
            reply = self._exactly_once(cid, seq, inner, st=st)
        else:
            # codec hellos + raw heartbeat pings from the follower
            # channel (the hello check must come FIRST: the blanket
            # ("ok", None) ack is what an OLD leader answers, which
            # clients read as version 0)
            hello = _codec.handle_hello(st.sock, msg)
            reply = hello if hello is not None else ("ok", None)
            if msg and msg[0] == "ping":
                # pings ride the follower's dedicated liveness socket;
                # hellos arrive on data sockets too and must not latch
                st.recv_kind = "ici_control_recv"
                reply_kind = "ici_control"
        self._reply(st, conns, reply, False, reply_kind)
        return True

    def _serve_lane(self, st, conns, pending):
        lane = st.lane
        if lane.dead():
            self._drop_lane(st)
            return False
        try:
            msg = lane.recv_request()
        except MXNetError:
            # a corrupt ring record poisons the whole lane (framing is
            # lost) — kill the lane; the follower's stall watchdog
            # fails over to TCP and replays its window
            self._drop_lane(st)
            return False
        if msg is None:
            return False
        if msg and msg[0] == "req":
            _, cid, seq, inner = msg[:4]
            self._note_heard(cid)
            if self._defer_collect(st, pending, cid, seq, inner, True):
                return True
            reply = self._exactly_once(cid, seq, inner, st=st)
        else:
            reply = ("ok", None)
        self._reply(st, conns, reply, True)
        return True

    def _defer_collect(self, st, pending, cid, seq, inner, via_shm):
        """Park a mesh_collect whose wire round is not registered yet.
        Blocking this pool thread on ``_handles`` instead would be a
        deadlock: another follower's mesh_push — the frame the round
        needs to complete — may be sitting unread on a connection this
        same thread owns.  Returns True when parked."""
        import time as _time
        if not inner or inner[0] != "mesh_collect":
            return False
        with self._cv:
            have = self._dedup.get(cid)
            if have is not None and have[0] == seq:
                return False   # replay with a cached reply — serve now
            if int(inner[1]) in self._handles or self._stop.is_set():
                return False   # resolvable (or failing fast) already
        pending.append((st, cid, seq, inner, via_shm,
                        _time.monotonic() + self._fanin_s))
        return True

    def _scan_pending(self, conns, pending):
        import time as _time
        if not pending:
            return False
        busy = False
        for item in list(pending):
            st, cid, seq, inner, via_shm, deadline = item
            with self._cv:
                have = self._dedup.get(cid)
                served = (int(inner[1]) in self._handles
                          or self._stop.is_set()
                          or (have is not None and have[0] == seq))
            if served:
                pending.remove(item)
                reply = self._exactly_once(cid, seq, inner, st=st)
                self._reply(st, conns, reply, via_shm)
                busy = True
            elif _time.monotonic() > deadline:
                pending.remove(item)
                self._reply(st, conns, (
                    "err", f"MXNetError: mesh leader {self._uri}: no "
                           f"wire round registered for collect seq "
                           f"{int(inner[1])} within {self._fanin_s}s"),
                    via_shm)
                busy = True
        return busy

    def _reply(self, st, conns, reply, via_shm, reply_kind="ici_sent"):
        """Send a reply back the way the request came: shm-borne
        requests get shm replies (falling back to the socket when the
        reply outgrows the ring — the follower polls both)."""
        from . import wirecodec as _codec
        from .kvstore_server import _send_msg
        if via_shm and st.lane is not None and not st.lane.dead():
            try:
                if st.lane.send_reply(
                        reply, binary_ok=_codec.sock_binary(st.sock)):
                    return
            except MXNetError:
                self._drop_lane(st)
        try:
            _send_msg(st.sock, reply, byte_kind=reply_kind)
        except (ConnectionError, OSError):
            self._drop_conn(st, conns)

    def _note_heard(self, cid):
        import time as _time
        if not isinstance(cid, (tuple, list)) or not cid:
            return
        try:
            rank = int(cid[0])
        except (TypeError, ValueError):
            return
        with self._cv:
            self._last_heard[rank] = _time.monotonic()

    def _drop_lane(self, st):
        lane, st.lane = st.lane, None
        if lane is None:
            return
        try:
            lane.mark_dead()
        except Exception:  # noqa: BLE001 — segment may be gone
            pass
        lane.close()

    def _drop_conn(self, st, conns):
        self._drop_lane(st)
        try:
            st.sock.close()
        except OSError:
            pass
        try:
            conns.remove(st)
        except ValueError:
            pass
        try:
            self._conns.remove(st.sock)
        except ValueError:
            pass

    def _exactly_once(self, cid, seq, inner, st=None):
        """Per-CLIENT single-slot dedup, keyed (client_id, seq) like
        the real server's window so a reconnect REPLAY — which arrives
        on a FRESH connection whose thread has no local state — still
        hits the cache instead of re-executing (a re-executed
        mesh_push would double a follower's gradient in the round).
        One slot per client suffices: mesh channels run window 1, so
        at most one envelope per follower is ever unacked.  A replay
        racing the original's in-flight execution parks until its
        reply is stored (the zombie-duplicate shape the real server's
        window also covers)."""
        with self._cv:
            while True:
                have = self._dedup.get(cid)
                if have is not None and have[0] == seq:
                    return have[1]
                if self._dedup_inflight.get(cid) != seq:
                    self._dedup_inflight[cid] = seq
                    break
                if not self._cv.wait(timeout=self._fanin_s):
                    return ("err", "mesh leader: duplicate envelope "
                                   "parked past the fan-in budget")
        rank = None
        if isinstance(cid, (tuple, list)) and cid:
            try:
                rank = int(cid[0])
            except (TypeError, ValueError):
                rank = None
        try:
            reply = ("ok", self._handle(inner, st=st, rank=rank))
        except Exception as exc:  # noqa: BLE001
            reply = ("err", f"{type(exc).__name__}: {exc}")
        with self._cv:
            self._dedup[cid] = (seq, reply)
            if self._dedup_inflight.get(cid) == seq:
                del self._dedup_inflight[cid]
            self._cv.notify_all()
        return reply

    def _handle(self, inner, st=None, rank=None):
        from . import profiler as _prof
        op = inner[0]
        if op == "mesh_push":  # protocol: replay(dedup-window) reply(none) codec(binary)
            _, seq, pairs = inner
            with self._cv:
                self._pushes.setdefault(int(seq), []).append(pairs)
                if rank is not None:
                    self._push_ranks.setdefault(int(seq), set()).add(rank)
                self._cv.notify_all()
            _prof.record_channel_event("kvstore.mesh_push")
            return None
        if op == "mesh_collect":  # protocol: replay(dedup-window) reply(key -> ndarray) codec(binary)
            _, seq, keys = inner
            seq = int(seq)
            with self._cv:
                ok = self._cv.wait_for(
                    lambda: seq in self._handles or self._stop.is_set(),
                    timeout=self._fanin_s)
                if not ok or self._stop.is_set():
                    raise MXNetError(
                        f"mesh leader {self._uri}: no wire round "
                        f"registered for collect seq {seq} within "
                        f"{self._fanin_s}s")
                entry = self._handles[seq]
            vals = entry[0].wait()   # thread-safe, idempotent
            with self._cv:
                entry[1] += 1
                if entry[1] >= self._n_followers:
                    self._handles.pop(seq, None)
            _prof.record_channel_event("kvstore.mesh_collect")
            return {k: vals[k] for k in keys}
        if op == "shm_hello":  # protocol: replay(idempotent) reply(lane version | err)
            # follower created a shared-memory lane and names its
            # segment; attach and serve this connection's traffic off
            # the ring from here on.  Idempotent: re-attaching the same
            # segment (reconnect replay) just replaces the attachment.
            from . import shmlane
            _, name = inner[:2]
            if st is None:
                raise MXNetError(
                    "mesh leader: shm_hello outside a connection")
            lane = shmlane.ShmLane.attach(str(name))
            self._drop_lane(st)
            st.lane = lane
            _prof.record_channel_event("kvstore.shm_attach")
            return shmlane.VERSION
        if op == "command":  # protocol: replay(pure) reply(none)
            return None   # follower channel flush token
        raise MXNetError(f"mesh leader: unknown op {op!r}")


class _MeshConnState:
    """Per-connection state owned by exactly one acceptor-pool thread:
    the socket, the (optional) attached shm lane serving it, and the
    latched byte-kind for liveness pings."""

    __slots__ = ("sock", "lane", "recv_kind")

    def __init__(self, sock):
        self.sock = sock
        self.lane = None
        self.recv_kind = "ici_recv"


class _MeshAcceptor:
    """One worker of the mesh leader's bounded serve pool.  The accept
    thread hands connections over via ``inbox`` (a queue.Queue — the
    put/get pair is the happens-before edge for the socket object);
    ``poke()`` wakes the worker out of its select so adoption and
    shutdown are prompt."""

    def __init__(self, leader):
        import queue
        import socket
        self.inbox = queue.Queue()
        self.wake_r, self._wake_w = socket.socketpair()
        self.wake_r.setblocking(False)
        # analysis: allow(bare-thread): pool threads serve sockets the leader owns — close() closes those sockets and pokes the wake pipe, so a crashed worker surfaces as dropped connections and loud channel failures on every follower it served
        self.thread = threading.Thread(target=leader._serve_pool,
                                       args=(self,), daemon=True)
        self.thread.start()

    def adopt(self, conn):
        self.inbox.put(conn)
        self.poke()

    def poke(self):
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    def close_wake(self):
        for s in (self.wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass


class KVStoreDistAsync(KVStore):
    """Worker-side kvstore ``dist_async`` (reference: kvstore_dist.h worker
    + the server's immediate-apply branch, kvstore_dist_server.h:405-430).

    Keys are routed to servers by ``crc32(key) % num_servers`` — the
    deterministic key→server partition that replaces the reference's
    ``EncodeKey``/PSKV round-robin (kvstore_dist.h:60).

    Arrays above ``MXNET_KVSTORE_BIGARRAY_BOUND`` elements (default
    1e6, the reference's default, kvstore_dist.h:60) are STRIPED
    row-wise across all servers: each stripe is its own server-side key
    (``<key>@s<i>``), so pushes/pulls of big tensors serialize and
    apply in parallel on every server (reference: PSKV slices big
    arrays across servers).  Server-side optimizer state is then
    per-stripe — identical math for elementwise optimizers (SGD/Adam/
    …); per-LAYER optimizers (LARS/LAMB trust ratios) see per-stripe
    norms instead, exactly the reference's striping caveat.
    """

    def __init__(self, uris=None, roster_member=None, rank=None):
        super().__init__("dist_async")
        # explicit rank override (tests running several worker stores —
        # different ranks — in ONE process, where the DMLC env can only
        # name one; the launcher path leaves it None)
        self._rank_override = None if rank is None else int(rank)
        if uris is None:
            uris = os.environ.get("MXT_SERVER_URIS", "")
        elif not isinstance(uris, str):
            uris = ",".join(uris)
        if not uris:
            raise MXNetError(
                "kvstore 'dist_async' needs running parameter servers: "
                "launch with `python tools/launch.py -n W -s S cmd...` "
                "(MXT_SERVER_URIS is set by the launcher; a serving "
                "replica passes param_servers= explicitly) — see "
                "docs/design/kvstore.md")
        from .base import env as _env
        uri_list = uris.split(",")
        # -- elastic membership (mxnet_tpu.membership) --------------------
        # The env uris are only the BOOTSTRAP set: under
        # MXNET_KVSTORE_ELASTIC the authoritative server list is the
        # coordinator's roster (generation-numbered; server 0).  A
        # ``roster_member`` client registers as a live worker rank
        # (barriers count it, silence evicts it); an observer — the
        # serving replica's refresh client — follows the roster without
        # ever joining it.
        self._elastic = bool(_env("MXNET_KVSTORE_ELASTIC", False))
        self._roster_member = (self._elastic if roster_member is None
                               else bool(roster_member)) and self._elastic
        self._roster_gen = 0
        self._roster_servers = list(uri_list)
        self._bootstrap_servers = list(uri_list)
        self._live_workers = None
        self._failovers = 0           # coordinator successions ridden
        self._coordinator_slot = 0    # bootstrap slot of the coordinator
        self._barrier_seq = 0         # per-worker barrier sequence
        # _elastic_lock guards the pull cache / push log quartet (and
        # the order deque): _cache_value runs on whatever thread
        # resolves a _PullHandle — the mesh-collect server threads
        # included — concurrently with _log_push/_push_mark on the
        # pushing thread.  Unsynchronized, the absorb accounting
        # (read-modify-write of _push_log_absorbed, del of list
        # prefixes) can lose or double re-push log entries across a
        # roster bump (hb-sanitizer finding, ISSUE 15).  All four
        # structures are hb-tracked.
        self._elastic_lock = threading.Lock()
        self._pull_cache: Dict[str, np.ndarray] = _hb.track(
            {}, "KVStoreDistAsync._pull_cache")
        self._push_log: Dict[str, list] = _hb.track(
            {}, "KVStoreDistAsync._push_log")
        # absolute per-key push positions: _push_log_seq counts every
        # push ever logged, _push_log_absorbed how many of those the
        # cache has absorbed.  A pull's cache sync may only absorb
        # pushes issued BEFORE the pull was ENQUEUED (its "mark") — the
        # fused driver resolves pulls chunks later, with newer pushes
        # already in flight, and absorbing those would drop them from
        # the elastic re-push log (the exact-bookkeeping half of the
        # ISSUE 14 replan contract)
        self._push_log_seq: Dict[str, int] = _hb.track(
            {}, "KVStoreDistAsync._push_log_seq")
        self._push_log_absorbed: Dict[str, int] = _hb.track(
            {}, "KVStoreDistAsync._push_log_absorbed")
        self._push_log_order = None
        self._push_log_cap = int(_env("MXNET_KVSTORE_ELASTIC_PUSH_LOG",
                                      256))
        if self._elastic:
            import collections
            self._push_log_order = _hb.track(
                collections.deque(), "KVStoreDistAsync._push_log_order")
            # dial the bootstrap uris in order until one answers the
            # roster op: slot 0 is the coordinator in the common case,
            # but a late joiner may arrive AFTER churn — any surviving
            # server forwards the op one hop to the live coordinator
            # (kvstore_server "roster_fwd"), so reaching ANY of them is
            # enough to converge onto the current roster
            join_msg = (("roster_join", "worker", self.rank)
                        if self._roster_member else ("roster_get",))
            coord = reply = last_exc = None
            for i, u in enumerate(uri_list):
                try:
                    c = _ServerConn(u, connect_timeout=(
                        60.0 if i == 0 else 15.0), rank=self.rank)
                except MXNetError as exc:
                    last_exc = exc
                    continue
                try:
                    reply = c.submit(join_msg, wait=True)
                    coord = c
                    break
                except MXNetError as exc:
                    last_exc = exc
                    c.close(retry=False)
            if reply is None:
                raise MXNetError(
                    "kvstore dist_async: no bootstrap server answered "
                    f"the roster (tried {uri_list}): {last_exc}")
            self._conns = [coord]
            gen, servers, workers = reply[0], reply[1], reply[2]
            if len(reply) > 3:
                # worker-join replies carry the cohort's barrier floor:
                # seeding our sequence there keeps raw barrier seqs
                # globally aligned, so arrivals pair exactly even
                # against a failover successor with empty barrier state
                self._barrier_seq = int(reply[3])
            conns = []
            for u in servers:
                conns.append(coord if u == coord._uri
                             else _ServerConn(u, rank=self.rank))
            if coord._uri not in servers:
                coord.close(retry=False)
            self._conns = conns
            self._roster_gen = int(gen)
            self._roster_servers = list(servers)
            self._live_workers = list(workers)
            from . import profiler as _prof
            _prof.record_channel_gauge("kvstore.roster_generation",
                                       self._roster_gen)
        else:
            self._conns = [_ServerConn(u, rank=self.rank)
                           for u in uri_list]
        self._bigarray_bound = int(float(os.environ.get(
            "MXNET_KVSTORE_BIGARRAY_BOUND", "1000000")))
        self._stripes: Dict[str, list] = {}  # key -> row boundaries
        self._stripes_nservers = len(self._conns)
        self._last_moved_keys = set()
        self._closed = False
        # wire compression: error-feedback residuals live worker-side,
        # one per WIRE key (stripes quantize independently).  Env
        # activation mirrors the launcher's env-propagation model, so a
        # whole job flips compression on without touching user code.
        self._gc_residual: Dict[str, np.ndarray] = _hb.track(
            {}, "kvstore._gc_residual")
        # row-sparse pushes keep their residuals PER GLOBAL ROW ID
        # ({base_key: {row_id: fp32 row}}) so a restripe can drop
        # exactly the rows whose owning server changed
        # (membership.moved_row_spans) instead of nuking whole keys —
        # the PR 7 lesson applied at row granularity.  _sparse_shapes
        # remembers each sparse key's full table shape for that
        # arithmetic (and for re-routing logged sparse pushes).
        self._sparse_residual: Dict[str, Dict[int, np.ndarray]] = \
            _hb.track({}, "kvstore._sparse_residual")
        self._sparse_shapes: Dict[str, tuple] = _hb.track(
            {}, "kvstore._sparse_shapes")
        self._sparse_wire = bool(_env("MXNET_KVSTORE_SPARSE", True))
        self._sparse_cutover = float(_env(
            "MXNET_KVSTORE_SPARSE_DENSITY_CUTOVER", 0.5))
        ctype = os.environ.get("MXNET_KVSTORE_COMPRESSION", "")
        if ctype and ctype != "none":
            self.set_gradient_compression({
                "type": ctype,
                "threshold": float(os.environ.get(
                    "MXNET_KVSTORE_COMPRESSION_THRESHOLD", "0.5"))})
        # pushes at or below this many payload bytes coalesce into one
        # multi-key envelope per server when pushed as a key list
        self._coalesce_bytes = int(float(os.environ.get(
            "MXNET_KVSTORE_COALESCE_BYTES", "16384")))
        # silence on any worker↔server channel becomes visible job-wide
        from . import distributed as _dist
        _dist._register_dead_node_source(self)
        # -- hierarchical tier (MXNET_KVSTORE_HIERARCHY) ------------------
        # Workers sharing a host form a mesh group: gradients allreduce
        # in-mesh (parallel.mesh.local_allreduce_sum — ICI when the
        # devices allow it) and ONLY the per-host leader ships the
        # reduced gradient over the TCP wire, fanning the pulled
        # weights back in-mesh — wire bytes per step drop by ~the
        # workers-per-host factor (docs/PERF_NOTES.md round 11).
        self._hier = False
        self._mesh_leader = None    # leader-side endpoint
        self._mesh_conn = None      # follower-side channel to the leader
        self._mesh_group = None
        self._mesh_push_seq = 0
        self._mesh_pull_seq = 0
        if bool(_env("MXNET_KVSTORE_HIERARCHY", False)):
            self._init_hierarchy()

    # -- identity (no jax.distributed needed: workers are independent) ------
    @property
    def rank(self) -> int:
        if getattr(self, "_rank_override", None) is not None:
            return self._rank_override
        return int(os.environ.get("DMLC_WORKER_ID", "0"))

    @property
    def num_workers(self) -> int:
        # elastic: the LIVE roster's worker count, not the launch-time
        # env — joins and evictions move it mid-job
        if self._elastic and self._live_workers is not None:
            return max(1, len(self._live_workers))
        return int(os.environ.get("DMLC_NUM_WORKER", "1"))

    def _conn_of(self, k: str) -> _ServerConn:
        # routing math lives in membership.server_index — the handoff
        # planner derives placement from the same function, so the two
        # can never diverge
        from .membership import server_index
        return self._conns[server_index(k, len(self._conns))]

    # -- hierarchical tier (MXNET_KVSTORE_HIERARCHY) --------------------------
    def _init_hierarchy(self):
        """Resolve this worker's host group (membership.mesh_group over
        the launch topology) and bring up its side of the mesh tier:
        the leader binds the group's loopback endpoint (_MeshLeader),
        followers dial it.  A one-member group (or a 1-worker job) is
        flat — the tier quietly stays off."""
        from .base import env as _env
        from . import membership as _mem
        if self._elastic:
            raise MXNetError(
                "MXNET_KVSTORE_HIERARCHY does not compose with "
                "MXNET_KVSTORE_ELASTIC yet: the mesh group is derived "
                "from the static launch topology, and a roster bump "
                "would strand the in-host tier (docs/ROBUSTNESS.md).  "
                "Run elastic jobs flat — their fused driver already "
                "rides the _PullHandle replan path")
        per_host = int(_env("MXNET_KVSTORE_WORKERS_PER_HOST", 0))
        if per_host <= 0:
            raise MXNetError(
                "MXNET_KVSTORE_HIERARCHY=1 needs the host topology: "
                "launch with `tools/launch.py --workers-per-host N` "
                "(which also allocates MXT_MESH_URIS), or set "
                "MXNET_KVSTORE_WORKERS_PER_HOST and MXT_MESH_URIS "
                "explicitly")
        nworkers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        leader, members, gi = _mem.mesh_group(
            self.rank, range(nworkers), per_host)
        if len(members) <= 1:
            return   # a single-member group has nothing to reduce
        mesh_uris = os.environ.get("MXT_MESH_URIS", "")
        uris = [u for u in mesh_uris.split(",") if u]
        if gi >= len(uris):
            raise MXNetError(
                f"MXNET_KVSTORE_HIERARCHY: no mesh endpoint for host "
                f"group {gi} in MXT_MESH_URIS={mesh_uris!r} — launch "
                "with tools/launch.py --workers-per-host, or export "
                "one host:port per group")
        self._hier = True
        self._mesh_group = members
        if self.rank == leader:
            self._mesh_leader = _MeshLeader(
                uris[gi], n_followers=len(members) - 1,
                follower_ranks=[r for r in members if r != leader])
        else:
            # window 1: the replay window is one envelope, which the
            # leader's one-slot dedup makes exactly-once (loopback
            # RTTs are noise next to the wire round this tier removes)
            self._mesh_conn = _ServerConn(
                uris[gi], window=1, rank=self.rank,
                byte_kinds=("ici_sent", "ici_recv"))
            # same-host fast path: one memcpy into a shared-memory
            # ring instead of a socket round-trip (MXNET_KVSTORE_SHM;
            # falls back to TCP silently if the leader predates the
            # lane or the segment can't be created)
            self._mesh_conn.setup_shm_lane()

    def _mesh_reduce(self, pairs, contribs):
        """In-mesh sum of the leader's own gradients with every
        follower's round contribution — parallel.mesh.local_allreduce_sum
        (psum-on-devices when the local mesh allows, stacked jnp sum on
        the CPU stub).  Key sets must match: the group runs the same
        SPMD program."""
        from .parallel.mesh import local_allreduce_sum
        by_key = [dict(c) for c in contribs]
        reduced = []
        for k, agg in pairs:
            parts = [agg]
            for c in by_key:
                if k not in c:
                    raise MXNetError(
                        f"hierarchical push: follower contribution is "
                        f"missing key {k!r} — the group's push rounds "
                        "have diverged (mesh members must run the same "
                        "program)")
                parts.append(c[k])
            if any(isinstance(p, RowSparsePayload) for p in parts):
                reduced.append((k, self._merge_sparse(parts)))
                continue
            reduced.append((k, np.asarray(
                local_allreduce_sum(parts), dtype=agg.dtype)))
        return reduced

    @staticmethod
    def _merge_sparse(parts):
        """Merge one mesh round's contributions for a row-sparse key
        into ONE deduped sparse sum: indices unioned, rows landing on
        the same id accumulated — the leader ships a single
        RowSparsePayload instead of every member's index set.  A mixed
        round (a member crossed the density cutover and densified its
        copy) degrades to the dense sum, since a dense contribution
        already touches every row."""
        if not all(isinstance(p, RowSparsePayload) for p in parts):
            dense = None
            for p in parts:
                if isinstance(p, RowSparsePayload):
                    rows = np.asarray(p.data)
                    d = np.zeros((p.nrows,) + rows.shape[1:], rows.dtype)
                    np.add.at(d, np.asarray(p.indices, np.int64), rows)
                else:
                    d = np.asarray(p)
                dense = d if dense is None else dense + d
            return dense
        allidx = np.concatenate(
            [np.asarray(p.indices, np.int64) for p in parts])
        allrows = np.concatenate(
            [np.asarray(p.data) for p in parts], axis=0)
        uniq, inv = np.unique(allidx, return_inverse=True)
        summed = np.zeros((uniq.size,) + allrows.shape[1:],
                          allrows.dtype)
        np.add.at(summed, inv, allrows)
        return RowSparsePayload(uniq, parts[0].nrows, summed)

    # -- big-array striping --------------------------------------------------
    def _stripe_plan(self, k: str, shape):
        """Row boundaries for a striped key, or None.  Deterministic from
        (key, shape, num_servers) — the math lives in
        :func:`membership.stripe_plan` so handoff planning and the
        worker can never diverge — and every worker computes the
        identical plan with no coordination.

        Plans are cached per key; the cache is valid ONLY for the server
        count it was derived against.  A server-count change without
        :meth:`_reset_stripe_plans` is a HARD error: a stale plan routes
        rows to the wrong servers silently (the elastic roster path
        clears the cache on every roster bump; nothing else may change
        the connection list)."""
        if self._stripes and self._stripes_nservers != len(self._conns):
            raise MXNetError(
                "kvstore dist_async: the server count changed "
                f"({self._stripes_nservers} -> {len(self._conns)}) with "
                "stripe plans still cached — a stale plan silently "
                "routes rows to the wrong servers.  Membership changes "
                "must go through the elastic roster path "
                "(MXNET_KVSTORE_ELASTIC=1), which calls "
                "_reset_stripe_plans() on every roster bump")
        if k in self._stripes:
            return self._stripes[k]
        if "@s" in k:
            # '@s' is the reserved stripe-suffix separator: a user key
            # 'w@s0' would collide with stripe 0 of key 'w' on the server
            # and be mangled by Optimizer._mult_index (ADVICE r5).  Every
            # op (init/push/pull/row_sparse_pull) derives its plan here,
            # so this one check covers the whole surface.
            raise MXNetError(
                f"kvstore dist_async: key {k!r} contains the reserved "
                "stripe separator '@s' — rename the parameter")
        from . import membership as _mem
        plan = _mem.stripe_plan(k, shape, len(self._conns),
                                self._bigarray_bound)
        self._stripes[k] = plan
        self._stripes_nservers = len(self._conns)
        return plan

    def _reset_stripe_plans(self):
        """Invalidate every cached stripe plan (the roster changed: row
        boundaries and owners must re-derive against the live server
        set).  The elastic path calls this inside ``_apply_roster``."""
        self._stripes.clear()
        self._stripes_nservers = len(self._conns)

    def _stripe_conn(self, k: str, i: int) -> _ServerConn:
        # consecutive stripes land on consecutive servers, offset by the
        # key hash so different big keys don't all start at server 0
        # (membership.stripe_server_index: shared with handoff planning)
        from .membership import stripe_server_index
        return self._conns[stripe_server_index(k, i, len(self._conns))]

    # -- elastic membership (worker half; mxnet_tpu.membership) --------------
    def _coordinator_conn(self) -> _ServerConn:
        """The channel to the CURRENT roster coordinator — derived via
        membership.coordinator_uri (the worker-side twin of the
        server's _coordinator_addr, one source of truth for both).
        Connections are kept in roster order, so this is conns[0]
        except transiently mid-repair."""
        from .membership import coordinator_uri
        curi = coordinator_uri(self._roster_servers)
        for c in self._conns:
            if c._uri == curi:
                return c
        return self._conns[0]

    def _elastic_attempt(self, fn):
        """Run one kv op; under MXNET_KVSTORE_ELASTIC a channel failure
        triggers a roster repair (report the dead server, re-derive
        striping against the surviving set, hand off state, re-push the
        logged updates a dead server took with it) and ONE retry of the
        op against the new generation.  Non-elastic behavior is
        bit-identical to before: the failure propagates."""
        if not self._elastic:
            return fn()
        attempts = 0
        while True:
            try:
                return fn()
            except MXNetError:
                attempts += 1
                if attempts > 2 or not self._elastic_repair():
                    raise

    def _elastic_repair(self) -> bool:
        """Span-wrapped entry: a repair episode (and the handoff inside
        it) shows up on the merged cluster timeline as one
        ``kv.repair`` span — the observable form of "this worker rode a
        roster bump" (docs/OBSERVABILITY.md)."""
        with _tr.span("kv.repair", cat="elastic"):
            return self._elastic_repair_impl()

    def _elastic_repair_impl(self) -> bool:
        """Converge this worker onto the live roster after a failure.
        Returns True when anything changed (retry is worth it): a
        generation bump was applied, or a poisoned-but-alive connection
        was re-dialed.

        The COORDINATOR going down is just another membership event:
        this worker independently elects
        ``membership.elect_successor(roster, dead)`` — the same pure
        arithmetic every other observer computes, no votes — and
        reports the death THERE.  The successor verifies the death with
        its own probe, rebuilds the ledger at max(reported
        generation)+1 and answers with the post-succession roster; the
        ordinary three-phase handoff then reconstructs the dead
        coordinator's stripes.  Only every-server-dead is
        unrecoverable (elect_successor returns None)."""
        from . import membership as _mem
        from . import profiler as _prof
        dead, poisoned = [], []
        for c in self._conns:
            if (c._err is not None and c._sock is None) or c.is_dead():
                dead.append(c)
            elif c._err is not None:
                poisoned.append(c)
        dead_uris = {c._uri for c in dead}
        coord_uri = _mem.coordinator_uri(self._roster_servers)
        succession = coord_uri in dead_uris
        # flight-recorder evidence BEFORE any wire work: even if this
        # worker dies mid-repair, its bundle names who it saw dead and
        # that a repair was in flight (tools/postmortem.py correlates
        # these across survivors)
        for u in sorted(dead_uris):
            _health.note("peer_dead", uri=u,
                         coordinator=bool(u == coord_uri))
        _health.note("repair.begin", dead=sorted(dead_uris),
                     poisoned=[c._uri for c in poisoned])
        reply = None
        while True:
            if coord_uri in dead_uris:
                succ_uri = _mem.elect_successor(self._roster_servers,
                                                dead_uris)
                if succ_uri is None:
                    return False   # every server dead: nothing to elect
                target = next((c for c in self._conns
                               if c._uri == succ_uri), None)
                if target is None:
                    return False   # conns/roster diverged: no dial
            else:
                target = self._coordinator_conn()
            try:
                # report the dead coordinator FIRST: the hint lets the
                # successor verify + promote inside this very request
                for uri in sorted(dead_uris, key=lambda u: u != coord_uri):
                    reply = target.submit(
                        ("roster_dead", "server", uri), wait=True)
                    _prof.record_channel_event("kvstore.eviction_reported")
                if reply is None:
                    reply = target.submit(("roster_get",), wait=True)
                break
            except MXNetError:
                if target._err is not None and target._sock is None \
                        and target._uri not in dead_uris:
                    # the elected target ITSELF died before answering
                    # (simultaneous multi-server preemption): its
                    # channel is now hard evidence — add it to the dead
                    # set and walk the election to the next slot, the
                    # same probe-walk the server side runs
                    dead_uris.add(target._uri)
                    succession = True
                    continue
                return False   # an app refusal / unreachable roster
        gen, servers, workers = reply
        if int(gen) == self._roster_gen and not dead and not poisoned:
            return False
        try:
            self._apply_roster(int(gen), servers, workers)
        except MXNetError as exc:
            # a roster-listed server died between the coordinator's view
            # and our dial: report it so the NEXT repair converges on the
            # shrunken roster, and let the original failure propagate —
            # aborting the retry here must not strand the conn list
            # half-applied (it hasn't been: _apply_roster swaps conns
            # only after every dial succeeded)
            uri = next((u for u in servers if u in str(exc)), None)
            if uri is not None:
                try:
                    target.submit(("roster_dead", "server", uri),
                                  wait=True)
                except MXNetError:
                    pass
            return False
        if succession:
            self._failovers += 1
            _prof.record_channel_event(
                "kvstore.coordinator_failover_observed")
            _health.note("failover_observed",
                         coordinator_slot=self._coordinator_slot)
        _health.note("repair.end", generation=self._roster_gen)
        return True

    def _elastic_refresh(self):
        """Pull the roster and converge if it moved (the cheap path a
        barrier-reply generation bump triggers)."""
        with _tr.span("kv.refresh", cat="elastic"):
            reply = self._coordinator_conn().submit(("roster_get",),
                                                    wait=True)
            gen, servers, workers = reply
            if int(gen) != self._roster_gen:
                self._apply_roster(int(gen), servers, workers)

    def _apply_roster(self, gen, servers, workers):
        """Converge onto roster generation ``gen``: rebuild the
        connection list in roster order (reusing healthy channels,
        re-dialing poisoned ones, closing departed ones), invalidate
        every stripe plan, ship the optimizer to newly-joined servers,
        then hand off state for every key whose wire layout moved."""
        from . import membership as _mem
        from . import profiler as _prof
        old_servers = list(self._roster_servers)
        by_uri = {c._uri: c for c in self._conns}
        conns, fresh = [], []
        try:
            for u in servers:
                c = by_uri.pop(u, None)
                if c is not None and (c._err is not None or c.is_dead()):
                    c.close(retry=False)
                    c = None
                if c is None:
                    # short dial budget: a roster-listed server that
                    # cannot be reached within 10s most likely died
                    # between the coordinator's view and ours — the
                    # caller reports it dead and retries on the smaller
                    # roster instead of blocking a full connect window
                    c = _ServerConn(u, connect_timeout=10.0,
                                    rank=self.rank)
                    fresh.append((u, c))
                conns.append(c)
        except MXNetError:
            for _u, c in fresh:
                c.close(retry=False)
            raise
        for c in by_uri.values():
            c.close(retry=False)
        self._conns = conns
        self._roster_gen = int(gen)
        self._roster_servers = list(servers)
        self._live_workers = list(workers)
        self._reset_stripe_plans()
        self._last_moved_keys = set()
        _prof.record_channel_event("kvstore.roster_bump")
        _prof.record_channel_gauge("kvstore.roster_generation",
                                   self._roster_gen)
        # every connection was just rebuilt against the live roster:
        # outstanding channel poison is repaired, not outstanding
        _health.clear_channel_poison()
        _health.note("roster_bump", generation=self._roster_gen)
        # which bootstrap slot leads now (-1 = a joined-later server):
        # a failover is observable as this gauge moving off slot 0
        curi = _mem.coordinator_uri(servers)
        self._coordinator_slot = (
            self._bootstrap_servers.index(curi)
            if curi in self._bootstrap_servers else -1)
        _prof.record_channel_gauge("kvstore.coordinator_slot",
                                   self._coordinator_slot)
        # a joined-mid-job server has no updater yet: every worker ships
        # the optimizer (idempotent — same object) before any state or
        # gradient can reach the new shard
        if self._optimizer is not None:
            blob = pickle.dumps(self._optimizer)
            from .kvstore_server import K_CONTROLLER
            for _u, c in fresh:
                if _u not in old_servers:
                    c.submit(("command", K_CONTROLLER, blob), wait=True)
        with self._elastic_lock:
            cache_shapes = {k: v.shape
                            for k, v in self._pull_cache.items()}
        moved = _mem.plan_handoff(
            cache_shapes, old_servers, servers, self._bigarray_bound)
        self._last_moved_keys = set(moved)
        if moved and self._gc_residual:
            # compression error-feedback residuals are keyed by WIRE key
            # and shaped like the OLD stripe spans: under the new layout
            # a moved key's residual would broadcast-add into the wrong
            # rows (or crash on shape mismatch).  Dropping it loses at
            # most one pending quantum per element — the bounded error
            # class compression already accepts — and the buffer re-grows
            # from zero on the next push.  Unmoved keys keep identical
            # wire spans, so their residuals stay valid.
            moved_set = set(moved)
            for wk in [w for w in self._gc_residual
                       if _mem.base_key(w) in moved_set]:
                del self._gc_residual[wk]
        if moved and self._sparse_residual:
            # row-sparse residuals are keyed by GLOBAL row id, so the
            # restripe arithmetic can be exact: drop only the rows whose
            # owning server changed (membership.moved_row_spans) — a row
            # that stayed with its server keeps its un-drained error,
            # the whole point of keying residuals per row (PR 7's
            # moved-key lesson applied at row granularity)
            moved_set = set(moved)
            for bk in [b for b in self._sparse_residual
                       if b in moved_set]:
                shape = self._sparse_shapes.get(bk) \
                    or cache_shapes.get(bk)
                if shape is None:
                    # no recorded geometry to compute spans against:
                    # dropping the whole bank is the safe degradation
                    del self._sparse_residual[bk]
                    continue
                spans = _mem.moved_row_spans(
                    bk, shape, old_servers, servers,
                    self._bigarray_bound)
                bank = self._sparse_residual[bk]
                for rid in [r for r in bank
                            if any(lo <= r < hi for lo, hi in spans)]:
                    del bank[rid]
                if not bank:
                    del self._sparse_residual[bk]
        if moved:
            self._handoff(moved, old_servers)

    def _handoff(self, moved, old_servers):
        """Striped-state handoff after a roster bump, in three ordered
        phases (docs/ROBUSTNESS.md has the sequence diagram):

        1. **quorum re-push of values** — every worker re-pushes its
           last-synced full value of each moved key under the NEW
           layout; the server applies the FIRST arrival per (wire key,
           generation) and acks the rest idempotently, so the racing
           duplicates (and replays through connection kills) are
           harmless.  The applied handoff purges the key's stale wire
           forms, so in-flight old-layout pushes are absorbed into the
           reset.
        2. **optimizer-state restripe** — per-stripe states gathered
           from the coordinator's snapshot of the departed servers plus
           ``get_states`` of the survivors, merged and re-sliced along
           the new plan (exact for elementwise state; a killed server
           with no banked snapshot degrades to fresh state for its
           stripes).
        3. **re-push of logged updates** — each worker re-applies every
           gradient it pushed since its last pull of a moved key (the
           updates a SIGKILLed server took to its grave, or that the
           handoff reset absorbed).  Phases 1+2 are awaited before 3 so
           re-pushed gradients can never be wiped by a later handoff."""
        from . import membership as _mem
        from . import profiler as _prof
        gen = self._roster_gen
        servers = self._roster_servers
        # The whole handoff — and each of its three protocol phases —
        # is a span, so a roster bump's repair window reads off the
        # merged cluster timeline instead of only off the
        # failover_rebuild_s gauge (docs/OBSERVABILITY.md).  The wire
        # behavior is UNCHANGED: values and states all enqueue before
        # any await (max pipelining); the shared await of phases 1+2
        # completes inside the states span, and phase 3 still starts
        # only after it.
        hsp = _tr.span_begin("kv.handoff", cat="elastic",
                             args={"moved": len(moved),
                                   "generation": int(gen)})
        try:
            # gather old-layout optimizer state BEFORE any value handoff
            # is issued: the first value handoff of a key PURGES its
            # stale wire forms (and their states) on the survivors —
            # collecting after would read back nothing
            with _tr.span("handoff.collect", cat="elastic"):
                per_wire = self._collect_handoff_states(moved, old_servers)
            # one consistent snapshot of the moved keys' cached values
            # and logged gradients: the wire work below must not hold
            # the elastic lock (it blocks on replies), and reading the
            # live structures per-key would race a concurrent
            # _cache_value from an in-flight handle resolve
            with self._elastic_lock:
                cache_snap = {k: self._pull_cache.get(k) for k in moved}
                log_snap = {k: list(self._push_log.get(k, ()))
                            for k in moved}
            pendings = []
            # per-phase flight-recorder breadcrumbs: with MXNET_TRACE=0
            # the spans vanish but the postmortem can still name the
            # repair phase in flight from the bundles alone (the ISSUE
            # 13 acceptance's trace-independence half)
            _health.note("handoff.values", moved=len(moved),
                         generation=int(gen))
            with _tr.span("handoff.values", cat="elastic"):
                for k in moved:
                    val = cache_snap.get(k)
                    if val is None:
                        continue
                    for wk, uri, part in _mem.restripe_value(
                            k, val, servers, self._bigarray_bound):
                        part = np.ascontiguousarray(part)
                        _prof.record_channel_bytes("handoff",
                                                   int(part.nbytes))
                        pendings.append(
                            self._conns[servers.index(uri)].request(
                                ("handoff", gen, wk, part, k)))
            _health.note("handoff.states", generation=int(gen))
            with _tr.span("handoff.states", cat="elastic"):
                if per_wire:
                    for k in moved:
                        shape = cache_snap[k].shape
                        old_plan = _mem.stripe_plan(
                            k, shape, len(old_servers),
                            self._bigarray_bound)
                        new_plan = _mem.stripe_plan(
                            k, shape, len(servers), self._bigarray_bound)
                        restriped = _mem.restripe_states(
                            k, per_wire, old_plan, new_plan)
                        layout = _mem.wire_layout(k, shape, servers,
                                                  self._bigarray_bound)
                        for wk, st in restriped.items():
                            uri = layout[wk][0]
                            pendings.append(
                                self._conns[servers.index(uri)].request(
                                    ("handoff_state", gen, wk, st, k)))
                for p in pendings:
                    _await(p)
            _prof.record_channel_event("kvstore.handoff_round")
            _health.note("handoff.repush", generation=int(gen))
            with _tr.span("handoff.repush", cat="elastic"):
                for k in moved:
                    for grad in log_snap.get(k, ()):
                        _prof.record_channel_event("kvstore.orphan_repush")
                        self._route_push(k, grad)
        finally:
            _tr.span_end(hsp)

    def _collect_handoff_states(self, moved, old_servers):
        """{old wire key: np state} for the moved keys: the departed
        servers' stripes from the coordinator's banked snapshots, the
        survivors' from a live ``get_states``.  Returns {} when no
        optimizer is installed (nothing to restripe)."""
        from .kvstore_server import _restricted_loads, _state_to_np
        departed = [u for u in old_servers
                    if u not in self._roster_servers]
        per_wire = {}
        for u in departed:
            try:
                snap = self._coordinator_conn().submit(
                    ("roster_snapshot", u), wait=True)
            except MXNetError:
                snap = None
            if snap:
                for wk, st in snap.get("states", {}).items():
                    per_wire[str(wk)] = st
        have_updater = False
        for c in self._conns:
            try:
                blob = c.submit(("get_states", False), wait=True)
            except MXNetError:
                continue
            if blob is None:
                continue
            have_updater = True
            for wk, st in _restricted_loads(blob).items():
                per_wire[str(wk)] = _state_to_np(st)
        return per_wire if have_updater else {}

    def _route_push(self, k: str, agg):
        """Send one (possibly compressed) push of a full gradient under
        the CURRENT stripe plan — the shared tail of push() and the
        orphan re-push.  A logged row-sparse gradient re-routes through
        the same per-stripe sparse planner as the original push."""
        if isinstance(agg, RowSparsePayload):
            for _wk, conn, msg in self._sparse_wire_entries(k, agg):
                conn.submit(msg, wait=False)
            return
        plan = self._stripe_plan(k, agg.shape)
        if plan is None:
            self._conn_of(k).submit(
                ("push", k, self._wire_push_payload(k, agg)), wait=False)
        else:
            for i in range(len(plan) - 1):
                wk = f"{k}@s{i}"
                self._stripe_conn(k, i).submit(
                    ("push", wk, self._wire_push_payload(
                        wk, agg[plan[i]:plan[i + 1]])),
                    wait=False)

    def _push_mark(self, k: str) -> int:
        """The key's current absolute push position — captured at pull
        ENQUEUE time so the later cache sync absorbs exactly the pushes
        that pull observed (per-conn FIFO: everything sent before the
        pull request, nothing after)."""
        with self._elastic_lock:
            return self._push_log_seq.get(k, 0)

    def _cache_value(self, k: str, arr, mark=None):
        """Remember the last synced full value of ``k`` (the quorum
        re-push source) and absorb the log entries the value reflects:
        everything up to ``mark`` (the pull's enqueue position), or the
        whole log when ``mark`` is None (init/assign — the value IS the
        authoritative state)."""
        if not self._elastic:
            return
        arr = np.asarray(arr)
        with self._elastic_lock:
            self._pull_cache[k] = arr
            seq = self._push_log_seq.get(k, 0)
            if mark is None or mark > seq:
                mark = seq
            absorbed = self._push_log_absorbed.get(k, 0)
            n = mark - absorbed
            if n > 0:
                entries = self._push_log.get(k)
                if entries:
                    del entries[:min(n, len(entries))]
                    if not entries:
                        self._push_log.pop(k, None)
            self._push_log_absorbed[k] = max(absorbed, mark)

    def _log_push(self, k: str, agg: np.ndarray):
        """Remember one pushed gradient until a pull of ``k`` that
        observed it syncs it into the cache (bounded by
        MXNET_KVSTORE_ELASTIC_PUSH_LOG entries; the oldest fall off —
        best-effort for jobs that never pull)."""
        if not self._elastic:
            return
        if not isinstance(agg, RowSparsePayload):
            agg = np.asarray(agg)
        with self._elastic_lock:
            self._push_log.setdefault(k, []).append(agg)
            self._push_log_seq[k] = self._push_log_seq.get(k, 0) + 1
            self._push_log_order.append(k)
            while len(self._push_log_order) > self._push_log_cap:
                old = self._push_log_order.popleft()
                entries = self._push_log.get(old)
                if entries:
                    entries.pop(0)
                    # a cap-dropped entry counts as absorbed so later
                    # marks keep addressing the list front correctly
                    self._push_log_absorbed[old] = \
                        self._push_log_absorbed.get(old, 0) + 1
                    if not entries:
                        self._push_log.pop(old, None)

    # -- kv ops --------------------------------------------------------------
    def init(self, key, value):
        """First-arriving init wins at the server (all workers call init;
        the server keeps one authoritative value)."""
        with _tr.span("kv.init"):
            self._elastic_attempt(lambda: self._init_impl(key, value))

    def _init_impl(self, key, value):
        keys, values = self._canon(key, value)
        for k, vs in zip(keys, values):
            arr = np.asarray(vs[0].asnumpy())
            plan = self._stripe_plan(k, arr.shape)
            if plan is None:
                self._conn_of(k).submit(("init", k, arr), wait=True)
            else:
                pendings = [
                    self._stripe_conn(k, i).request(
                        ("init", f"{k}@s{i}", arr[plan[i]:plan[i + 1]]))
                    for i in range(len(plan) - 1)]
                for p in pendings:
                    _await(p)
            self._cache_value(k, arr)

    def _wire_push_payload(self, wire_key, arr):
        """Compress one push payload when compression is on (2bit keeps
        its error-feedback residual here, keyed by WIRE key so stripes
        quantize independently); otherwise the raw array."""
        gc = self._gcompress
        if gc is None or not gc.active:
            return arr
        return gc.compress(wire_key, arr, self._gc_residual)

    @staticmethod
    def _payload_nbytes(payload) -> int:
        from .compression import WirePayload
        if isinstance(payload, RowSparsePayload):
            data = payload.data
            if isinstance(data, WirePayload):
                data = data.data
            return int(data.nbytes) + int(payload.indices.nbytes)
        data = payload.data if isinstance(payload, WirePayload) \
            else payload
        return int(data.nbytes)

    def _sparse_agg(self, k, vs):
        """Merge one key's device copies into a raw RowSparsePayload
        (sorted unique GLOBAL row ids, duplicate rows summed) without
        EVER densifying, or None when the sparse wire doesn't apply —
        values not row-sparse, the knob off, or the touch density past
        MXNET_KVSTORE_SPARSE_DENSITY_CUTOVER (at which point the dense
        path's tighter per-element packing wins).  Runs BEFORE
        ``_reduce``: reducing through ``._data`` would lazily densify
        the RowSparseNDArray and the wire would never see sparsity."""
        from .ndarray.sparse import RowSparseNDArray
        if not self._sparse_wire \
                or not all(isinstance(v, RowSparseNDArray) for v in vs):
            return None
        nrows = int(vs[0].shape[0])
        idx_parts = [np.asarray(v.indices.asnumpy(), np.int64)
                     for v in vs]
        row_parts = [np.asarray(v.data.asnumpy()) for v in vs]
        allidx = np.concatenate(idx_parts)
        allrows = np.concatenate(row_parts, axis=0)
        uniq, inv = np.unique(allidx, return_inverse=True)
        if uniq.size and (int(uniq[0]) < 0 or int(uniq[-1]) >= nrows):
            raise MXNetError(
                f"row-sparse push of key {k!r}: row ids span "
                f"[{int(uniq[0])}, {int(uniq[-1])}], key has "
                f"{nrows} rows")
        if uniq.size > self._sparse_cutover * nrows:
            return None
        summed = np.zeros((uniq.size,) + allrows.shape[1:],
                          allrows.dtype)
        np.add.at(summed, inv, allrows)
        self._sparse_shapes[k] = tuple(vs[0].shape)
        return RowSparsePayload(uniq, nrows, summed)

    def _wire_sparse_payload(self, base_key, global_ids, wire_ids,
                             rows, nrows):
        """Build the on-wire RowSparsePayload for one destination:
        ``wire_ids`` are LOCAL to the receiving stripe (its row 0),
        while compression residuals stay keyed by ``base_key`` +
        GLOBAL row id — so a restripe drops exactly the moved rows'
        residuals and nothing else."""
        ids = np.ascontiguousarray(np.asarray(wire_ids, np.int64))
        gc = self._gcompress
        if gc is None or not gc.active:
            return RowSparsePayload(ids, nrows,
                                    np.ascontiguousarray(rows))
        # the per-key row bank is itself shared across pushes and the
        # restripe GC — track it at row granularity too
        bank = self._sparse_residual.setdefault(
            base_key, _hb.track({}, "kvstore._sparse_residual[%s]"
                                % base_key))
        return RowSparsePayload(
            ids, nrows, gc.compress_rows(global_ids, rows, bank))

    def _sparse_wire_entries(self, k, p):
        """Plan one row-sparse push: ``[(wire_key, conn, msg)]`` with
        one entry per stripe the index set actually touches — an
        untouched stripe sends NOTHING, which is the whole wire win."""
        from . import membership as _mem
        from . import profiler as _prof
        idx = np.asarray(p.indices, np.int64)
        if idx.size == 0:
            return []
        rows = np.asarray(p.data)
        shape = self._sparse_shapes.get(k, (p.nrows,) + rows.shape[1:])
        plan = self._stripe_plan(k, shape)
        _prof.record_channel_count("kvstore.sparse_rows", int(idx.size))
        if plan is None:
            payload = self._wire_sparse_payload(k, idx, idx, rows,
                                                p.nrows)
            return [(k, self._conn_of(k), ("push", k, payload))]
        out = []
        for i, local_ids, pos in _mem.sparse_route(plan, idx):
            wk = f"{k}@s{i}"
            payload = self._wire_sparse_payload(
                k, idx[pos], local_ids,
                np.ascontiguousarray(rows[pos]),
                plan[i + 1] - plan[i])
            out.append((wk, self._stripe_conn(k, i),
                        ("push", wk, payload)))
        return out

    def push(self, key, value, priority=0):
        """Locally reduce, then hand to the channel — returns immediately;
        the server applies the update when the push arrives (async SGD).
        Striped keys push one row-slice per server, in parallel.

        A LIST push coalesces small keys bound for the same server into
        ONE multi-key envelope (``MXNET_KVSTORE_COALESCE_BYTES`` per-key
        bound) — small tensors stop paying a whole frame+ack each, the
        comms analog of the reference's per-key engine-op batching.

        Elastic note: push is fire-and-forget, so it must NOT be blanket-
        retried (earlier keys of this call may already sit in healthy
        server queues — a retry would double-apply them).  Instead the
        call is planned first and submitted second: a submit that hits a
        failed channel repairs the roster, then re-routes only the
        REMAINING entries — entries for keys whose layout moved are
        skipped, because the repair already re-pushed them from the push
        log."""
        keys, values = self._canon(key, value)
        with _tr.span("kv.push", args={"keys": len(keys)}):
            pairs = []
            for k, vs in zip(keys, values):
                sp = self._sparse_agg(k, vs)
                pairs.append((k, sp) if sp is not None
                             else (k, np.asarray(self._reduce(vs))))
            self._push_aggregated(pairs)

    def _push_aggregated(self, pairs):
        """Plan and submit one push round of already-reduced HOST
        gradients ``[(key, np.ndarray), ...]`` — the shared tail of
        :meth:`push` and the fused-dist chunk driver (which reads a
        whole chunk's gradients back in ONE stacked device_get and must
        not re-enter through NDArray wrappers).  Compression, striping,
        same-server coalescing and the elastic push log all live here,
        so the two entry points can never diverge on the wire.

        Under MXNET_KVSTORE_HIERARCHY this call IS one mesh round: a
        follower deposits its raw gradients with the host-group leader
        (in-host "ici" bytes, no compression — the error-feedback
        residual lives where the wire is) and returns; the leader
        blocks for the group's round, reduces in-mesh
        (``kv.mesh_reduce``) and ships ONE summed push per key through
        the normal plan below (``kv.leader_ship`` — compression,
        striping and coalescing all compose on the reduced
        gradient)."""
        if self._hier:
            seq = self._mesh_push_seq
            self._mesh_push_seq += 1
            if self._mesh_conn is not None:   # follower
                self._mesh_conn.submit(
                    ("mesh_push", seq,
                     [(k, a if isinstance(a, RowSparsePayload)
                       else np.ascontiguousarray(a)) for k, a in pairs]),
                    wait=False)
                return
            with _tr.span("kv.mesh_reduce", cat="hier",
                          args={"seq": seq, "keys": len(pairs)}):
                contribs = self._mesh_leader.collect_push(seq)
                pairs = self._mesh_reduce(pairs, contribs)
            with _tr.span("kv.leader_ship", cat="hier",
                          args={"keys": len(pairs)}):
                self._push_planned(pairs)
            return
        self._push_planned(pairs)

    def _push_planned(self, pairs):
        """The wire half of a push round: compression, striping,
        same-server coalescing, the elastic push log."""
        small: Dict[int, list] = {}   # conn index -> [(wire_key, payload)]
        planned = []                  # (base_key, conn, msg)
        for k, agg in pairs:
            if isinstance(agg, RowSparsePayload):
                if np.asarray(agg.indices).size == 0:
                    continue   # nothing touched: nothing rides, nothing logged
                self._log_push(k, agg)
                for wk, conn, msg in self._sparse_wire_entries(k, agg):
                    if (wk == k and len(pairs) > 1
                            and self._payload_nbytes(msg[2])
                            <= self._coalesce_bytes):
                        # unstriped tiny sparse pushes coalesce like
                        # dense ones; striped wire keys stay standalone
                        # (a push_multi reroute re-hashes by entry key)
                        small.setdefault(
                            self._conns.index(conn), []).append(
                                (k, msg[2]))
                    else:
                        planned.append((k, conn, msg))
                continue
            self._log_push(k, agg)
            plan = self._stripe_plan(k, agg.shape)
            if plan is None:
                payload = self._wire_push_payload(k, agg)
                conn = self._conn_of(k)
                if (len(pairs) > 1
                        and self._payload_nbytes(payload)
                        <= self._coalesce_bytes):
                    small.setdefault(self._conns.index(conn), []).append(
                        (k, payload))
                else:
                    planned.append((k, conn, ("push", k, payload)))
            else:
                for i in range(len(plan) - 1):
                    wk = f"{k}@s{i}"
                    planned.append((k, self._stripe_conn(k, i), (
                        "push", wk, self._wire_push_payload(
                            wk, agg[plan[i]:plan[i + 1]]))))
        for ci, entries in small.items():
            if len(entries) == 1:
                planned.append((entries[0][0], self._conns[ci],
                                ("push", entries[0][0], entries[0][1])))
            else:
                planned.append((None, self._conns[ci],
                                ("push_multi", entries)))
        self._submit_planned(planned)

    def _submit_planned(self, planned):
        """Submit planned push envelopes; on a channel failure in
        elastic mode, repair once and re-route the remainder under the
        new layout (moved keys skipped — the repair's log re-push owns
        them)."""
        for idx, (_k, conn, msg) in enumerate(planned):
            try:
                conn.submit(msg, wait=False)
            except MXNetError:
                if not self._elastic or not self._elastic_repair():
                    raise
                self._reroute_planned(planned[idx:])
                return

    def _reroute_planned(self, rest):
        """Re-route the unsent tail of a push call after a repair.  Keys
        the repair moved are dropped here (their full logged gradients
        were already re-pushed under the new layout); unmoved keys keep
        their wire keys and go to the same URI's fresh channel."""
        moved = self._last_moved_keys
        for k, _old_conn, msg in rest:
            if msg[0] == "push_multi":
                for ek, payload in msg[1]:
                    if ek not in moved:
                        self._conn_of(ek).submit(("push", ek, payload),
                                                 wait=False)
            elif k not in moved:
                wk = msg[1]
                if "@s" in wk:
                    base, i = wk.rsplit("@s", 1)
                    self._stripe_conn(base, int(i)).submit(msg, wait=False)
                else:
                    self._conn_of(wk).submit(msg, wait=False)

    def assign(self, key, value):
        """Store value(s) verbatim on the owning server(s) — bypasses
        the installed updater (see :meth:`KVStore.assign`).  Awaited:
        when this returns, every later ``pull`` observes the value (the
        serving version-bump publication contract).  Idempotent, so the
        elastic path may retry it whole after a roster repair."""
        with _tr.span("kv.assign"):
            self._elastic_attempt(lambda: self._assign_impl(key, value))

    def _assign_impl(self, key, value):
        keys, values = self._canon(key, value)
        pendings = []
        for k, vs in zip(keys, values):
            arr = np.asarray(vs[0].asnumpy())
            plan = self._stripe_plan(k, arr.shape)
            if plan is None:
                pendings.append(self._conn_of(k).request(("assign", k, arr)))
            else:
                pendings.extend(
                    self._stripe_conn(k, i).request(
                        ("assign", f"{k}@s{i}", arr[plan[i]:plan[i + 1]]))
                    for i in range(len(plan) - 1))
            self._cache_value(k, arr)
        for p in pendings:
            _await(p)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Fetch the server's CURRENT weight — possibly mid-stream of other
        workers' pushes; staleness is the async contract.

        All requests are enqueued before any reply is awaited, so an
        N-key pull over S servers costs ~max-RTT, not N round trips
        (the reference gets the same overlap from engine-async ZPull);
        striped keys fetch every row-slice concurrently.  Idempotent —
        the elastic path retries it whole after a roster repair."""
        with _tr.span("kv.pull"):
            self._elastic_attempt(
                lambda: self._pull_impl(key, out, ignore_sparse))

    def _pull_impl(self, key, out, ignore_sparse):
        import jax.numpy as jnp
        assert out is not None
        keys, outs = self._canon(key, out)
        if self._hier:
            # one mesh round for the whole call: the leader runs (and
            # registers) the wire pull, followers collect in-host —
            # the same rendezvous sequence the fused driver uses, so
            # eager pulls and pull_async stay interchangeable
            handle = self.pull_async(
                list(keys), [tuple(os_[0].shape) for os_ in outs])
            vals = handle.wait()
            for k, os_ in zip(keys, outs):
                val = jnp.asarray(vals[k])
                for o in os_:
                    o._set_data(val.astype(o._data.dtype)
                                if o._data.dtype != val.dtype else val)
            return
        pendings = []
        marks = []
        for k, os_ in zip(keys, outs):
            # the plan is deterministic from (key, shape): a client that
            # never init'ed this key derives it from the out array
            plan = self._stripe_plan(k, tuple(os_[0].shape))
            marks.append(self._push_mark(k))
            if plan is None:
                pendings.append(self._conn_of(k).request(("pull", k)))
            else:
                pendings.append([
                    self._stripe_conn(k, i).request(("pull", f"{k}@s{i}"))
                    for i in range(len(plan) - 1)])
        for k, os_, pending, mark in zip(keys, outs, pendings, marks):
            # cache from the HOST-side wire replies before converting to
            # jnp: caching the device array instead would cost an extra
            # unrecorded device->host readback per key per pull in
            # elastic mode (the sync-free gates exist to prevent exactly
            # that class of regrowth)
            if isinstance(pending, list):
                val_np = np.concatenate(
                    [np.asarray(_await(p)) for p in pending], axis=0)
            else:
                val_np = np.asarray(_await(pending))
            # the completed pull is this worker's sync point for k: the
            # cache becomes the quorum re-push value, and every logged
            # push the pull OBSERVED (up to its enqueue mark) is
            # absorbed into it
            self._cache_value(k, val_np, mark=mark)
            val = jnp.asarray(val_np)
            for o in os_:
                o._set_data(val.astype(o._data.dtype)
                            if o._data.dtype != val.dtype else val)

    def ship_chunk_steps(self, names, grads_np, shapes):
        """The shared SHIP leg of the fused-dist chunk drivers
        (Module._run_steps_fused_dist and Trainer step_k's dist path —
        one implementation so the wire contract can never diverge):
        push one chunk's per-step gradients in STEP order — the server's
        momentum/schedule state must advance once per step, exactly as
        the eager loop ships — with the small same-server keys of each
        step coalescing into one envelope, then enqueue the next
        non-blocking pull and return its handle."""
        with _tr.span("kv.ship_chunk",
                      args={"steps": int(grads_np[0].shape[0])}):
            for s in range(grads_np[0].shape[0]):
                self._push_aggregated(
                    [(n, np.ascontiguousarray(g[s]))
                     for n, g in zip(names, grads_np)])
            return self.pull_async(list(names), list(shapes))

    def pull_async(self, keys, shapes):
        """Enqueue a batched pull of ``keys`` and return a
        :class:`_PullHandle` immediately — the non-blocking half of the
        fused-dist driver's wire round: the requests ride the pipelined
        window now (per-server FIFO, so the replies observe every prior
        push from THIS worker), and ``handle.wait()`` collects the host
        values later, after the next chunk's compute has been
        dispatched.  ``shapes`` supplies each key's full logical shape
        so the stripe plan derives without an out array.

        Transport faults recover transparently through the channel's
        reconnect+replay; under MXNET_KVSTORE_ELASTIC a HARD channel
        failure triggers a roster repair from inside ``wait()`` and the
        handle REPLANS its unserved tail against the new stripe layout
        (:meth:`_PullHandle._replan`) — the fused driver and elastic
        membership compose (docs/ROBUSTNESS.md replan contract).

        Under MXNET_KVSTORE_HIERARCHY a follower's pull is one
        ``mesh_collect`` against the host-group leader (the weight
        fan-in rides the in-host mesh, zero wire bytes); the leader
        runs the real wire round and registers the handle so collects
        resolve against the SAME round."""
        if isinstance(keys, str):
            keys, shapes = [keys], [shapes]
        keys = [_key(k) for k in keys]
        if self._hier:
            seq = self._mesh_pull_seq
            self._mesh_pull_seq += 1
            if self._mesh_conn is not None:   # follower
                pending = self._mesh_conn.request(
                    ("mesh_collect", seq, list(keys)))
                return _MeshPullHandle(self, keys, pending)
        entries = []
        for k, shape in zip(keys, shapes):
            entries.append(self._elastic_attempt(
                lambda k=k, shape=shape: self._enqueue_pull(k, shape)))
        handle = _PullHandle(self, entries)
        if self._hier:
            self._mesh_leader.publish_handle(seq, handle)
        return handle

    def _enqueue_pull(self, k, shape):
        """Issue the per-stripe pull requests of one key under the
        CURRENT layout; returns the handle entry (the replan unit)."""
        plan = self._stripe_plan(k, tuple(shape))
        parts = []
        if plan is None:
            rows = int(shape[0]) if shape else 0
            parts.append([0, rows, k,
                          self._conn_of(k).request(("pull", k)), None])
        else:
            for i in range(len(plan) - 1):
                wk = f"{k}@s{i}"
                parts.append([plan[i], plan[i + 1], wk,
                              self._stripe_conn(k, i).request(
                                  ("pull", wk)), None])
        return {"key": k, "shape": tuple(shape), "parts": parts,
                "mark": self._push_mark(k)}

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows from the owning server — O(rows)
        on the wire (reference: DataHandleRowSparse,
        kvstore_dist_server.h:211).  Same out-array semantics as the
        local store: RowSparseNDArray gets values+indices, dense gets a
        scatter.  Requests pipeline like pull."""
        with _tr.span("kv.row_sparse_pull"):
            self._elastic_attempt(
                lambda: self._row_sparse_pull_impl(key, out, row_ids))

    def _row_sparse_pull_impl(self, key, out, row_ids):
        import jax.numpy as jnp
        from . import membership as _mem
        assert out is not None and row_ids is not None
        keys, outs = self._canon(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        reqs = []
        for k, os_, rid in zip(keys, outs, row_ids):
            if _mem.STRIPE_SEP in k:
                # same reservation the local store enforces: a user key
                # carrying the separator collides with striped wire keys
                raise MXNetError(
                    f"kvstore {self.type}: key {k!r} contains the "
                    f"reserved stripe separator "
                    f"'{_mem.STRIPE_SEP}' — rename the parameter")
            idx = np.unique(np.asarray(rid.asnumpy(), dtype=np.int64))
            # out (dense or row-sparse) carries the full logical shape, so
            # a fresh client derives the stripe plan just like pull()
            plan = self._stripe_plan(k, tuple(os_[0].shape))
            if plan is not None and idx.size and (
                    idx[0] < 0 or idx[-1] >= plan[-1]):
                raise MXNetError(
                    f"row id out of range for key {k!r}: ids span "
                    f"[{idx[0]}, {idx[-1]}], key has {plan[-1]} rows")
            if plan is None:
                reqs.append((idx, self._conn_of(k).request(
                    ("pull_rowsparse", k, idx))))
            else:
                # route each global row id to its stripe
                # (membership.sparse_route); stripes are contiguous and
                # idx is sorted, so concatenating the per-stripe
                # replies in stripe order realigns with idx
                parts = [
                    (self._stripe_conn(k, i).request(
                        ("pull_rowsparse", f"{k}@s{i}", local)))
                    for i, local, _pos in _mem.sparse_route(plan, idx)]
                if not parts:
                    # the empty-idx degenerate still needs one reply
                    # to learn the row tail shape
                    parts = [self._stripe_conn(k, 0).request(
                        ("pull_rowsparse", f"{k}@s0",
                         np.zeros(0, np.int64)))]
                reqs.append((idx, (plan, parts)))
        for (idx, pending), (k, os_) in zip(reqs, zip(keys, outs)):
            if isinstance(pending, tuple):
                plan, parts = pending
                replies = [self._await_rows(p, k) for p in parts]
                rows = jnp.concatenate(
                    [jnp.asarray(r) for r, _shape in replies], axis=0)
                full_shape = (plan[-1],) + tuple(replies[0][1][1:])
            else:
                rows_np, full_shape = self._await_rows(pending, k)
                rows = jnp.asarray(rows_np)
            _write_row_sparse_out(os_, rows, idx, full_shape)

    @staticmethod
    def _await_rows(pending, k):
        """Await one pull_rowsparse reply, mapping the server's
        uninitialized-key error back to the TYPED KeyError the local
        store raises — the caller (e.g. a serving refresh probing for a
        key) must get a catchable KeyError, not an MXNetError that the
        elastic retry loop would spin on while the window sits wedged
        behind a request that can never succeed."""
        try:
            return _await(pending)
        except MXNetError as exc:
            msg = str(exc)
            if "KeyError" in msg and "uninitialized key" in msg:
                raise KeyError(
                    f"pull of uninitialized key {k!r}") from exc
            raise

    def set_optimizer(self, optimizer):
        """Ship the optimizer to the servers (reference kvstore.py:353:
        rank 0 pickles it; _send_command_to_servers head=0), then barrier
        so every worker sees the installed updater before pushing.
        Idempotent (same blob), so the elastic path retries it whole —
        and every worker KEEPS the optimizer so a server joining later
        can be armed during roster repair."""
        self._optimizer = optimizer
        self._elastic_attempt(lambda: self._ship_optimizer(optimizer))
        self.barrier()

    def _ship_optimizer(self, optimizer):
        if self.rank != 0 and not self._elastic:
            return
        if self.rank != 0 and self._elastic:
            # non-zero ranks still ship nothing at install time (rank 0
            # owns it, reference semantics) — they only re-arm JOINED
            # servers during repair, where every worker races
            # idempotently
            return
        blob = pickle.dumps(optimizer)
        from .kvstore_server import K_CONTROLLER
        for c in self._conns:
            c.submit(("command", K_CONTROLLER, blob), wait=True)

    def _send_command_to_servers(self, head, body):
        for c in self._conns:
            c.submit(("command", head, body), wait=True)

    def _owner_conn(self, wire_key: str) -> _ServerConn:
        """The connection of the server that OWNS a wire key (stripe
        suffix respected) — the shard whose copy of that key's optimizer
        state is authoritative."""
        if "@s" in wire_key:
            base, i = wire_key.rsplit("@s", 1)
            try:
                return self._stripe_conn(base, int(i))
            except ValueError:
                pass  # '@s' from a pre-guard key: fall through
        return self._conn_of(wire_key)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        """Gather each server shard's {key: state} dict and persist the
        merge, with the optimizer itself when dump_optimizer (same blob
        format as Updater.get_states — the states LIVE on the servers in
        this mode; reference: kvstore_dist_server.h:131).

        Each key's OWNER shard wins the merge: after a
        load_optimizer_states broadcast, non-owner shards may still hold
        stale loaded copies of other shards' keys (servers with an empty
        store return them all — the load→save relay case), and a plain
        connection-order union would let a stale copy overwrite the
        owner's fresh state (ADVICE r5)."""
        merged, opt_obj = {}, None
        per_server = []
        for c in self._conns:
            blob = c.submit(("get_states", dump_optimizer), wait=True)
            if blob is None:
                raise MXNetError("there is no optimizer installed on the "
                                 "servers (set_optimizer first)")
            # server-returned blob: decode through the transport
            # allowlist, like every other peer-supplied pickle
            from .kvstore_server import _restricted_loads
            loaded = _restricted_loads(blob)
            if dump_optimizer:
                states, opt_obj = loaded  # identical snapshot per server
            else:
                states = loaded
            per_server.append((c, states))
        for _c, states in per_server:      # any-server fallback first
            merged.update(states)
        for c, states in per_server:       # then the owner's copy wins
            for k, v in states.items():
                # updater keys round-trip through _key_int (numeric wire
                # keys become ints) — str() restores the wire key
                if self._owner_conn(k if isinstance(k, str)
                                    else str(k)) is c:
                    merged[k] = v
        with open(fname, 'wb') as fout:
            fout.write(pickle.dumps((merged, opt_obj) if dump_optimizer
                                    else merged))

    def load_optimizer_states(self, fname):
        """Broadcast the saved union to every server; each shard applies
        all keys and simply never touches the ones it doesn't own (and a
        later get_states returns only OWNED keys — kvstore_server.py —
        so the loaded copies of other shards' keys can never leak back
        stale into a subsequent save)."""
        with open(fname, 'rb') as fin:
            blob = fin.read()
        self.load_optimizer_states_blob(blob)

    def load_optimizer_states_blob(self, blob):
        """Broadcast an already-read optimizer-state blob (the gluon
        Trainer buffers the file contents when load_states runs before
        the optimizer has been shipped to the servers)."""
        for c in self._conns:
            c.submit(("set_states", blob), wait=True)

    def barrier(self):
        """Flush this worker's outstanding pushes, then rendezvous on
        the roster coordinator (reference: Postoffice::Barrier after
        engine drain).  The wait is unbounded, but a participant that
        dies mid-wait is NAMED — with its last-heartbeat age — in the
        static-roster failure; under MXNET_KVSTORE_ELASTIC the barrier
        RENEGOTIATES instead: the coordinator evicts the silent rank,
        re-targets the live worker set and wakes the parked survivors,
        and the reply carries the roster generation so a bump is
        discovered (and converged onto) at every sync point for free.

        Arrivals carry this worker's barrier SEQUENCE number, making
        them idempotent: when the COORDINATOR dies mid-wait, the elastic
        retry re-sends the SAME (rank, seq) arrival to the elected
        successor — released immediately if the rendezvous already
        happened before the reply was lost, counted once otherwise —
        so a failover can never skew the workers' barrier pairing."""
        # the flush is idempotent (a no-op command per channel), so a
        # channel death here repairs and retries cleanly
        with _tr.span("kv.barrier"):
            self._elastic_attempt(self._flush_all)
            self._barrier_seq += 1
            bseq = self._barrier_seq
            # the rendezvous is a registered health wait: parked past
            # MXNET_HEALTH_BARRIER_STALL_S the watchdog trips a typed
            # barrier_stall event and the status degrades — a wedged
            # barrier becomes a signal, not a silent hang
            wtok = _health.wait_begin("kv.barrier")
            try:
                payload = self._elastic_attempt(
                    lambda: self._coordinator_conn().submit(
                        ("barrier", bseq), wait=True))
            finally:
                _health.wait_end(wtok)
            if isinstance(payload, (tuple, list)) and len(payload) == 2:
                # the coordinator realigned this (re-)joined rank to the
                # cohort's pending rendezvous: adopt the effective
                # sequence so every later raw sequence is globally
                # aligned again
                payload, realign = payload
                self._barrier_seq = bseq + int(realign)
            if self._elastic and isinstance(payload, int) \
                    and payload != self._roster_gen:
                # the refresh rides the repair wrapper too: the
                # coordinator can die in the reply-to-refresh window, and
                # that death is as survivable as any other
                self._elastic_attempt(self._elastic_refresh)

    def _flush_all(self):
        if self._mesh_conn is not None:
            # a follower's queued mesh pushes must reach the leader
            # before its barrier arrival — the leader (also a barrier
            # participant) only arrives after shipping them, so the
            # classic "every prior push visible after barrier" contract
            # holds through the tier
            self._mesh_conn.flush()
        for c in self._conns:
            c.flush()

    def num_dead_nodes(self) -> int:
        """Number of server channels whose heartbeat has gone silent
        (reference: kvstore.h:328 get_num_dead_node — finally real)."""
        if self._closed:
            return 0
        return sum(1 for c in self._conns if c.is_dead())

    def server_stats(self, rank: int = 0) -> dict:
        """The full profiler snapshot of server ``rank`` over the wire —
        the universal ``("stats",)`` envelope every KVStoreServer
        answers (kvstore_server._stats_payload: dispatch/host-sync/
        channel counts, gauges, byte counters, latency rings, roster
        generation, and the coordinator's last-known-stats bank of dead
        peers).  ``distributed.cluster_stats()`` sweeps this across
        every live server."""
        if not 0 <= rank < len(self._conns):
            raise MXNetError(
                f"server rank {rank} out of range "
                f"(live servers: {len(self._conns)})")
        return self._conns[rank].submit(("stats",), wait=True)

    def close(self, stop_servers=False):
        from .kvstore_server import K_STOP_SERVER
        self._closed = True
        if self._mesh_conn is not None:
            self._mesh_conn.close(retry=False)
            self._mesh_conn = None
        if self._mesh_leader is not None:
            self._mesh_leader.close()
            self._mesh_leader = None
        self._hier = False
        if self._roster_member:
            # graceful departure: deregister so the surviving workers'
            # barriers re-target without waiting out a heartbeat timeout
            try:
                self._coordinator_conn().submit(
                    ("roster_leave", "worker", self.rank), wait=True)
            except MXNetError:
                pass  # the coordinator will evict us on silence instead
        # deliver queued pushes while the servers are still guaranteed up
        for c in self._conns:
            try:
                c.flush()
            except MXNetError:
                pass  # channel already dead — nothing left to deliver
        if stop_servers:
            # best-effort: with several workers closing concurrently,
            # another worker's kStopServer may tear the connection down
            # before our own command is acked
            for c in self._conns:
                try:
                    c.submit(("command", K_STOP_SERVER, None), wait=True)
                except MXNetError:
                    pass
        for c in self._conns:
            # after kStopServer the server is DELIBERATELY gone:
            # reconnect backoff during the final drain would only stall
            # teardown (retry=False makes faults fail fast there)
            c.close(retry=not stop_servers)


def create(name="local") -> KVStore:
    """reference: kvstore.py:534 create → KVStore::Create (kvstore.cc:34)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in ("local", "local_allreduce_cpu", "local_allreduce_device",
                "device", "tpu", "dist_sync", "dist_device_sync", "dist",
                "nccl"):
        return KVStore(name)
    if name == "dist_async":
        return KVStoreDistAsync()
    raise MXNetError(f"unknown kvstore type {name!r}")
