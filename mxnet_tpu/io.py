"""Data iterators (reference: python/mxnet/io.py; C++ side src/io/).

The heavy C++ pipeline of the reference (RecordIO chunk readers, OMP JPEG
decode, double-buffered prefetch — src/io/iter_image_recordio_2.cc) maps to:
host-side Python/np iterators here, a native C++ RecordIO/decode path in
``mxnet_tpu.recordio`` / ``mxnet_tpu/native``, and ``PrefetchingIter`` for
the double-buffering.  Device transfer overlaps compute because jax transfers
are async.
"""
from __future__ import annotations

import threading
from collections import namedtuple, OrderedDict
from typing import List, Optional

import numpy as np

from .base import MXNetError
from .ndarray import NDArray
from .ndarray.ndarray import array as nd_array


class DataDesc(namedtuple('DataDesc', ['name', 'shape'])):
    """Data description incl dtype/layout (reference: io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout='NCHW'):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype},{self.layout}]"

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find('N')

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One batch (reference: io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), "Data must be list of NDArrays"
        if label is not None:
            assert isinstance(label, (list, tuple)), "Label must be list of NDArrays"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        label_shapes = [l.shape for l in self.label] if self.label else None
        return f"{self.__class__.__name__}: data shapes: {data_shapes} " \
               f"label shapes: {label_shapes}"


class _ProducerError:
    """Exception captured in a background prefetch thread, re-raised on
    the CONSUMER side at the next ``next()`` — a dead worker must fail
    the epoch loudly, never truncate it silently."""

    def __init__(self, exc):
        self.exc = exc


class DataIter:
    """Iterator protocol (reference: io.py:176 DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference: io.py:278)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, 'default_bucket_key'):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Threaded double-buffered prefetch (reference: io.py:343; C++ analog
    dmlc::ThreadedIter in iter_prefetcher.h).

    ``device_put=True`` adds an async device-transfer stage IN the
    prefetch thread: batch t+1 starts its host→device transfer (an async
    ``jax.device_put``) while the consumer's program still computes on
    batch t — the jax_graft form of the reference's ThreadedIter overlap
    of IO with compute.  This is the feed stage for the multi-step
    driver (``Module.run_steps``): with K steps per dispatch and the
    next superbatch already in flight, the host's only per-dispatch work
    is the scan launch itself.  ``device`` selects the target jax device
    (default: jax's default device)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 device_put=False, device=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._device_put = device_put
        self._device = device
        # prefer the inner iterator's declared batch_size: for a
        # KBatchIter the provide_data leading dim is the STEP count k,
        # not the batch size (DataIter's default of 0 falls through to
        # the legacy shape-derived value)
        self.batch_size = getattr(iters[0], 'batch_size', 0) or \
            self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    batch = self.iters[i].next()
                    if self._device_put:
                        batch = self._transfer(batch)
                    self.next_batch[i] = batch
                except StopIteration:
                    self.next_batch[i] = None
                except BaseException as e:  # noqa: BLE001 — crossing a
                    # thread: park the failure for the consumer.  Without
                    # this the thread dies before setting data_ready and
                    # every later next() hangs forever — or, were the
                    # event set, the epoch would just END early: silent
                    # truncation of the training set.
                    self.next_batch[i] = _ProducerError(e)
                self.data_taken[i].clear()
                self.data_ready[i].set()
        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i], daemon=True)
            for i in range(self.n_iter)]
        for thread in self.prefetch_threads:
            thread.start()

    def _transfer(self, batch):
        """Start the async host→device transfer of every array in the
        batch (jax.device_put returns immediately; the copy proceeds in
        the background while the consumer computes on the previous
        batch).  Runs in the prefetch thread."""
        import jax

        def put(arrs):
            if arrs is None:
                return None
            return [NDArray(jax.device_put(a._data, self._device))
                    for a in arrs]

        return DataBatch(put(batch.data), put(batch.label),
                         pad=batch.pad, index=batch.index,
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()
        for thread in self.prefetch_threads:
            thread.join(timeout=1.0)

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        for batch in self.next_batch:
            if isinstance(batch, _ProducerError):
                # leave the error parked (data_ready stays set, taken
                # stays clear): every subsequent next() re-raises instead
                # of handing the worker more work
                raise MXNetError(
                    "PrefetchingIter: prefetch worker failed: %r"
                    % (batch.exc,)) from batch.exc
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Different pad size in the data batches"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], [])
            if self.next_batch[0].label is not None else None,
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class KBatchIter(DataIter):
    """Stack K consecutive batches of an inner iterator into ONE
    superbatch with a leading step axis — the feed shape of the
    multi-step driver (``Module.run_steps`` / ``Trainer.step_k``).

    Each ``next()`` pulls K batches from the inner iterator, stacks
    host-side (numpy — one contiguous buffer, so the superbatch crosses
    the host→device link as one transfer), and returns a DataBatch whose
    arrays are ``(k, batch, ...)``.  A trailing partial group (fewer
    than K batches left) is dropped by default (``last_group='discard'``)
    or emitted short (``'keep'``) — run_steps falls back to the eager
    driver for a short group's different leading dim, so training still
    consumes every batch.  Compose with ``PrefetchingIter(...,
    device_put=True)`` to overlap the superbatch transfer with the
    previous scanned program's compute."""

    def __init__(self, data_iter, k, last_group='discard'):
        super().__init__()
        if k < 1:
            raise MXNetError(f"KBatchIter: k must be >= 1, got {k}")
        if last_group not in ('discard', 'keep'):
            raise MXNetError("KBatchIter: last_group must be 'discard' "
                             "or 'keep'")
        self.data_iter = data_iter
        self.k = k
        self.last_group = last_group
        self.batch_size = data_iter.batch_size
        self._k_provide = lambda descs: [
            DataDesc(d.name, (self.k,) + tuple(d.shape),
                     getattr(d, 'dtype', np.float32))
            for d in descs]

    @property
    def provide_data(self):
        return self._k_provide(self.data_iter.provide_data)

    @property
    def provide_label(self):
        return self._k_provide(self.data_iter.provide_label)

    def reset(self):
        self.data_iter.reset()

    def next(self):
        batches = []
        for _ in range(self.k):
            try:
                batches.append(self.data_iter.next())
            except StopIteration:
                break
        if not batches or (len(batches) < self.k
                           and self.last_group == 'discard'):
            raise StopIteration
        data = [nd_array(np.stack([np.asarray(b.data[i].asnumpy())
                                   for b in batches]))
                for i in range(len(batches[0].data))]
        label = None
        if batches[0].label:
            label = [nd_array(np.stack([np.asarray(b.label[i].asnumpy())
                                        for b in batches]))
                     for i in range(len(batches[0].label))]
        if len(batches) == self.k:
            pd, pl = self.provide_data, self.provide_label
        else:
            # short tail group ('keep' mode): the attached descs must
            # state the ACTUAL leading dim, not the nominal k
            kk = len(batches)
            pd = [DataDesc(d.name, (kk,) + tuple(d.shape[1:]),
                           getattr(d, 'dtype', np.float32))
                  for d in self.provide_data]
            pl = [DataDesc(d.name, (kk,) + tuple(d.shape[1:]),
                           getattr(d, 'dtype', np.float32))
                  for d in self.provide_label]
        return DataBatch(data, label, pad=batches[-1].pad,
                         provide_data=pd, provide_label=pl)


def _init_data(data, allow_empty, default_name):
    """reference: io.py _init_data."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = OrderedDict([(default_name, data[0])])
        else:
            data = OrderedDict(
                [('_%d_%s' % (i, default_name), d)
                 for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    for k, v in data.items():
        if not isinstance(v, NDArray):
            try:
                data[k] = nd_array(v)
            except Exception:
                raise TypeError(f"Invalid type '{type(v)}' for {k}")
    return list(data.items())


class NDArrayIter(DataIter):
    """In-memory iterator (reference: io.py:516 NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle='pad', data_name='data',
                 label_name='softmax_label'):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)

        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, nd_array(v.asnumpy()[self.idx], dtype=v.dtype))
                         for k, v in self.data]
            self.label = [(k, nd_array(v.asnumpy()[self.idx], dtype=v.dtype))
                          for k, v in self.label]

        if last_batch_handle == 'discard':
            new_n = self.data[0][1].shape[0] - \
                self.data[0][1].shape[0] % batch_size
            data_dict = OrderedDict(self.data)
            label_dict = OrderedDict(self.label)
            for k, _ in self.data:
                data_dict[k] = data_dict[k][:new_n]
            for k, _ in self.label:
                label_dict[k] = label_dict[k][:new_n]
            self.data = list(data_dict.items())
            self.label = list(label_dict.items())

        # keep numpy masters for fast batch slicing
        self._np_data = [(k, v.asnumpy()) for k, v in self.data]
        self._np_label = [(k, v.asnumpy()) for k, v in self.label]
        self.data_list = [x[1] for x in self.data] + \
            [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.data[0][1].shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size"
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == 'roll_over' and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + \
                (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        out = []
        for _, x in data_source:
            if self.cursor + self.batch_size <= self.num_data:
                sl = x[self.cursor:self.cursor + self.batch_size]
            else:
                pad = self.batch_size - self.num_data + self.cursor
                sl = np.concatenate([x[self.cursor:], x[:pad]], axis=0)
            out.append(nd_array(sl, dtype=sl.dtype))
        return out

    def getdata(self):
        return self._getdata(self._np_data)

    def getlabel(self):
        return self._getdata(self._np_label)

    def getpad(self):
        if self.last_batch_handle == 'pad' and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class CSVIter(DataIter):
    """CSV file iterator (reference: src/io/io.cc:150 CSVIter)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype='float32', **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=',', dtype=dtype, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=',', dtype=dtype, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = np.zeros((data.shape[0],), dtype=dtype)
        self._inner = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle='roll_over' if round_batch else 'discard',
            data_name='data', label_name='label')

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    def getdata(self):
        return self._inner.getdata()

    def getlabel(self):
        return self._inner.getlabel()

    def getpad(self):
        return self._inner.getpad()


def _read_idx(path):
    """Parse an IDX file (the MNIST container format)."""
    import gzip
    import struct
    op = gzip.open if path.endswith('.gz') else open
    with op(path, 'rb') as f:
        raw = f.read()
    zero, dtype_code, ndim = struct.unpack('>HBB', raw[:4])
    if zero != 0:
        raise MXNetError(f"{path}: not an IDX file")
    dims = struct.unpack('>' + 'I' * ndim, raw[4:4 + 4 * ndim])
    # IDX is big-endian throughout (including the payload)
    dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype('>i2'),
              0x0C: np.dtype('>i4'), 0x0D: np.dtype('>f4'),
              0x0E: np.dtype('>f8')}
    return np.frombuffer(raw, dtypes[dtype_code],
                         offset=4 + 4 * ndim).reshape(dims)


class MNISTIter(DataIter):
    """MNIST IDX-file iterator (reference: src/io/io.cc:259 MNISTIter,
    src/io/iter_mnist.cc — same params: image/label paths, flat,
    silent, shuffle, part/num_parts sharding)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, silent=False, seed=0,
                 part_index=0, num_parts=1, **kwargs):
        super().__init__(batch_size)
        imgs = _read_idx(image).astype(np.float32) / 255.0
        labs = _read_idx(label).astype(np.float32)
        if num_parts > 1:
            n = len(imgs) // num_parts
            imgs = imgs[part_index * n:(part_index + 1) * n]
            labs = labs[part_index * n:(part_index + 1) * n]
        if shuffle:
            order = np.random.RandomState(seed).permutation(len(imgs))
            imgs, labs = imgs[order], labs[order]
        imgs = imgs.reshape(len(imgs), -1) if flat else \
            imgs.reshape(len(imgs), 1, imgs.shape[1], imgs.shape[2])
        if not silent:
            import logging
            logging.info("MNISTIter: loaded %d images shape %s",
                         len(imgs), imgs.shape[1:])
        self._inner = NDArrayIter(imgs, labs, batch_size=batch_size,
                                  shuffle=False)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """LibSVM text-format iterator (reference: src/io/io.cc:200 LibSVMIter,
    src/io/iter_libsvm.cc).  Features batch as CSRNDArray (O(nnz)); dense
    consumers call ``.todense()`` / use ``csr.dot`` directly."""

    @staticmethod
    def _parse_libsvm(path):
        labels, rows_data, rows_idx = [], [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                idx, vals = [], []
                for tok in parts[1:]:
                    k, v = tok.split(':')
                    idx.append(int(k))
                    vals.append(float(v))
                rows_idx.append(np.asarray(idx, np.int64))
                rows_data.append(np.asarray(vals, np.float32))
        return labels, rows_data, rows_idx

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 label_libsvm=None, label_shape=None, round_batch=True,
                 part_index=0, num_parts=1, **kwargs):
        super().__init__(batch_size)
        ncol = int(np.prod(data_shape))
        labels, rows_data, rows_idx = self._parse_libsvm(data_libsvm)
        if label_libsvm is not None:
            # separate label file: its first column is the label
            # (reference: iter_libsvm.cc label_libsvm param)
            labels, _, _ = self._parse_libsvm(label_libsvm)
        if num_parts > 1:
            n = len(labels) // num_parts
            sl = slice(part_index * n, (part_index + 1) * n)
            labels, rows_data, rows_idx = \
                labels[sl], rows_data[sl], rows_idx[sl]
        self._labels = np.asarray(labels, np.float32)
        self._rows_data = rows_data
        self._rows_idx = rows_idx
        self._ncol = ncol
        self.batch_size = batch_size
        self.round_batch = round_batch
        self.provide_data = [DataDesc('data', (batch_size, ncol))]
        self.provide_label = [DataDesc('label', (batch_size,))]
        self._cursor = 0

    def reset(self):
        self._cursor = 0

    def next(self):
        from .ndarray.sparse import CSRNDArray
        n = len(self._labels)
        if self._cursor >= n:
            raise StopIteration
        take = list(range(self._cursor,
                          min(self._cursor + self.batch_size, n)))
        short = self.batch_size - len(take)
        if short:
            if not self.round_batch:
                raise StopIteration
            # wrap around to fill the final batch (reference: round_batch)
            take += list(range(short))
        self._cursor += self.batch_size
        rdat = [self._rows_data[i] for i in take]
        ridx = [self._rows_idx[i] for i in take]
        data = np.concatenate(rdat) if any(len(r) for r in rdat) else \
            np.zeros((0,), np.float32)
        indices = np.concatenate(ridx) if any(len(r) for r in ridx) else \
            np.zeros((0,), np.int64)
        indptr = np.zeros(self.batch_size + 1, np.int64)
        np.cumsum([len(r) for r in ridx], out=indptr[1:])
        csr = CSRNDArray(data, indices, indptr,
                         (self.batch_size, self._ncol))
        from .ndarray.ndarray import array as nd_array
        return DataBatch([csr], [nd_array(self._labels[take])],
                         pad=short,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class MXDataIter(DataIter):
    """Placeholder for native-backed iterators; the native RecordIO path
    registers its own iterators in mxnet_tpu.image / mxnet_tpu.recordio."""

    def __init__(self, *a, **kw):
        raise MXNetError("MXDataIter: use ImageRecordIter from "
                         "mxnet_tpu.image or NDArrayIter")
