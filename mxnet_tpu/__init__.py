"""mxnet_tpu — a TPU-native deep-learning framework with the capabilities of
Apache MXNet 0.12 (reference mounted at /root/reference), rebuilt from
scratch on JAX/XLA/Pallas/pjit.  See SURVEY.md for the blueprint.

Usage mirrors the reference: ``import mxnet_tpu as mx``.
"""
from .base import MXNetError, __version__
from .context import Context, cpu, cpu_pinned, gpu, tpu, num_gpus, num_tpus, \
    current_context
from . import base
from . import ops
from . import random
from . import autograd
from . import ndarray
from . import ndarray as nd
from . import name
from . import attribute
from .attribute import AttrScope
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import executor
from .executor import Executor
from . import initializer
from .initializer import Initializer
from . import optimizer
from . import optimizer as opt
from .optimizer import Optimizer
from . import lr_scheduler
from . import metric
from . import callback
from . import io
from .io import DataBatch, DataIter, DataDesc, NDArrayIter, ResizeIter, \
    PrefetchingIter, CSVIter
from .image_record_iter import ImageRecordIter, ImageRecordUInt8Iter
io.ImageRecordIter = ImageRecordIter   # reference API: mx.io.ImageRecordIter
io.ImageRecordUInt8Iter = ImageRecordUInt8Iter
# reference registers _v1 variants of the record iterators
# (src/io/io.cc:337-758, the pre-rewrite pipeline kept for compat);
# here there is one implementation, so _v1 is the same class
io.ImageRecordIter_v1 = ImageRecordIter
io.ImageRecordUInt8Iter_v1 = ImageRecordUInt8Iter
from .image.detection import ImageDetRecordIter
io.ImageDetRecordIter = ImageDetRecordIter  # reference: src/io/io.cc:581
from . import recordio
from . import image
from . import image as img
from . import kvstore as kv
from . import kvstore
from . import membership
from . import faultinject
from . import model
from . import serving
from . import module
from . import module as mod
from .module import Module, BaseModule
from . import profiler
from . import tracing
from . import health
from . import monitor
from .monitor import Monitor
from . import visualization
from . import visualization as viz
from . import test_utils
from . import operator
from . import operator as op
from . import serialization
from . import models
from . import parallel
from . import gluon
from . import rnn
from . import contrib
from . import notebook
from . import rtc

from .ndarray import NDArray

# A process launched with DMLC_ROLE=server becomes a blocking async
# parameter server the moment it imports this library, and exits when the
# job stops — so user training scripts run unmodified as server commands
# (reference: python/mxnet/kvstore_server.py:75 _init_kvstore_server_module
# called at import; servers started by tools/launch.py -s).
from . import kvstore_server as _kvstore_server
_kvstore_server._init_kvstore_server_module()
