"""Sparse NDArrays: row_sparse and CSR.

Scoped TPU-native design (SURVEY.md §7 "Hard parts": XLA has no native
sparse).  The reference implements storage types dense/row_sparse/CSR at the
NDArray level (include/mxnet/ndarray.h:58-62) with per-op storage-type
inference and dense fallback.  Here sparse arrays are explicit wrapper
classes holding dense component arrays (indices + values), chosen because on
TPU the only wins worth keeping are:

* row_sparse gradients for embeddings (gather/scatter-add — XLA handles
  these natively and efficiently),
* CSR x dense matmul via ``jax.experimental.sparse`` BCSR or segment-sum.

Any op without a sparse-aware path falls back to dense via ``.todense()``,
mirroring the reference's storage-fallback mechanism
(src/common/exec_utils.h SetupDefaultBlobsInOut).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from .ndarray import NDArray, _invoke


class BaseSparseNDArray(NDArray):
    pass


class RowSparseNDArray(BaseSparseNDArray):
    """values (nnz_rows, *row_shape) + indices (nnz_rows,) — reference:
    ndarray.h kRowSparseStorage."""

    def __init__(self, data, indices, shape, dtype=None):
        self._sp_data = data if isinstance(data, NDArray) else NDArray(data, dtype=dtype)
        self._sp_indices = indices if isinstance(indices, NDArray) else \
            NDArray(np.asarray(indices, dtype=np.int64), dtype="int64")
        self._sp_shape = tuple(shape)
        dense = jnp.zeros(self._sp_shape, self._sp_data._data.dtype).at[
            self._sp_indices._data.astype(jnp.int32)].set(self._sp_data._data)
        super().__init__(dense)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self):
        return self._sp_data

    @property
    def indices(self):
        return self._sp_indices

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data)
        raise MXNetError(f"cast {self.stype} -> {stype} unsupported")

    def todense(self):
        return NDArray(self._data)


class CSRNDArray(BaseSparseNDArray):
    """CSR matrix: data/indices/indptr (reference: ndarray.h kCSRStorage)."""

    def __init__(self, data, indices, indptr, shape, dtype=None):
        self._sp_data = data if isinstance(data, NDArray) else NDArray(data, dtype=dtype)
        self._sp_indices = indices if isinstance(indices, NDArray) else \
            NDArray(np.asarray(indices, dtype=np.int64), dtype="int64")
        self._sp_indptr = indptr if isinstance(indptr, NDArray) else \
            NDArray(np.asarray(indptr, dtype=np.int64), dtype="int64")
        self._sp_shape = tuple(shape)
        # dense materialization (fallback path)
        n_rows = shape[0]
        iptr = np.asarray(self._sp_indptr.asnumpy(), dtype=np.int64)
        rows = np.repeat(np.arange(n_rows), np.diff(iptr))
        dense = np.zeros(shape, dtype=np.asarray(self._sp_data.asnumpy()).dtype)
        dense[rows, self._sp_indices.asnumpy().astype(np.int64)] = \
            self._sp_data.asnumpy()
        super().__init__(dense)

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return self._sp_data

    @property
    def indices(self):
        return self._sp_indices

    @property
    def indptr(self):
        return self._sp_indptr

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data)
        raise MXNetError(f"cast {self.stype} -> {stype} unsupported")

    def todense(self):
        return NDArray(self._data)


def row_sparse_array(arg1, shape=None, dtype=None, ctx=None):
    if isinstance(arg1, (tuple, list)) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(data, indices, shape, dtype=dtype)
    # from dense
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    nz = np.where(np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(dense[nz], nz, dense.shape, dtype=dtype)


def csr_matrix(arg1, shape=None, dtype=None, ctx=None):
    if isinstance(arg1, (tuple, list)) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indices, indptr, shape, dtype=dtype)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    rows, cols = np.nonzero(dense)
    indptr = np.searchsorted(rows, np.arange(dense.shape[0] + 1))
    return CSRNDArray(dense[rows, cols], cols, indptr, dense.shape, dtype=dtype)


def cast_storage(arr, stype):
    """reference: tensor/cast_storage-inl.h"""
    if stype == "default":
        return NDArray(arr._data)
    dense = arr.asnumpy()
    if stype == "row_sparse":
        return row_sparse_array(NDArray(dense))
    if stype == "csr":
        return csr_matrix(NDArray(dense))
    raise MXNetError(stype)


def zeros(stype, shape, ctx=None, dtype=None):
    if stype == "row_sparse":
        return RowSparseNDArray(np.zeros((0,) + tuple(shape[1:])),
                                np.zeros((0,)), shape, dtype=dtype)
    if stype == "csr":
        return CSRNDArray(np.zeros((0,)), np.zeros((0,)),
                          np.zeros(shape[0] + 1), shape, dtype=dtype)
    raise MXNetError(stype)
