"""Sparse NDArrays: row_sparse and CSR — O(nnz) TPU-native design.

The reference implements storage types dense/row_sparse/CSR at the NDArray
level (include/mxnet/ndarray.h:58-62) with per-op storage-type inference
and dense fallback.  XLA has no native sparse tensors; what survives on
TPU — and what the reference actually uses sparse *for* — is:

* **row_sparse gradients for embeddings**: values (nnz_rows, d) + indices,
  produced by autograd without ever materializing the (vocab, d) dense
  gradient (autograd.backward sparse-leaf path), consumed by sparse
  optimizer updates that touch only those rows
  (reference: src/operator/optimizer_op.cc sparse SGD/Adam).
* **CSR × dense dot** via gather + scatter-add, O(nnz·k)
  (reference: src/operator/tensor/dot-inl.h DotCsrDnsDns).

Dense materialization still exists as the universal fallback (mirroring
the reference's storage fallback, src/common/exec_utils.h
SetupDefaultBlobsInOut) but it is LAZY: a sparse array densifies only when
a dense-only code path actually reads it, and ``DENSIFY_COUNT`` records
every such event so tests can assert hot paths stay sparse.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from .ndarray import NDArray

# incremented on every lazy dense materialization — tests assert this
# stays flat across sparse hot paths
DENSIFY_COUNT = 0


def _mark_densified():
    global DENSIFY_COUNT
    DENSIFY_COUNT += 1


class BaseSparseNDArray(NDArray):
    pass


class RowSparseNDArray(BaseSparseNDArray):
    """values (nnz_rows, *row_shape) + indices (nnz_rows,) — reference:
    ndarray.h kRowSparseStorage.  Dense payload is LAZY (O(nnz) until a
    dense-only op forces it)."""

    def __init__(self, data, indices, shape, dtype=None):
        self._sp_data = data if isinstance(data, NDArray) \
            else NDArray(data, dtype=dtype)
        self._sp_indices = indices if isinstance(indices, NDArray) else \
            NDArray(np.asarray(indices, dtype=np.int64), dtype="int64")
        self._sp_shape = tuple(shape)
        self._handle = object()
        self._ctx = None
        self._grad = None
        self._grad_req = "null"
        self._payload = None
        sp_data, sp_idx = self._sp_data, self._sp_indices

        def densify():
            _mark_densified()
            dense = jnp.zeros(self._sp_shape, sp_data._data.dtype).at[
                sp_idx._data.astype(jnp.int32)].add(
                    sp_data._data, mode="drop")
            self._set_data(dense)

        self._set_lazy(densify, aval=jax.ShapeDtypeStruct(
            self._sp_shape, jnp.dtype(self._sp_data.dtype)))

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self):
        return self._sp_data

    @property
    def indices(self):
        return self._sp_indices

    def retain(self, row_ids):
        """Keep only rows in row_ids (reference: sparse_retain op)."""
        rid = np.asarray(row_ids.asnumpy() if isinstance(row_ids, NDArray)
                         else row_ids).astype(np.int64)
        mask = np.isin(self._sp_indices.asnumpy(), rid)
        keep = np.where(mask)[0]
        return RowSparseNDArray(
            NDArray(jnp.take(self._sp_data._data, keep, axis=0)),
            self._sp_indices.asnumpy()[mask], self._sp_shape)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data)
        raise MXNetError(f"cast {self.stype} -> {stype} unsupported")

    def todense(self):
        return NDArray(self._data)

    def __repr__(self):
        return (f"\n<RowSparseNDArray {self._sp_shape} "
                f"nnz_rows={self._sp_indices.shape[0]}>")


class CSRNDArray(BaseSparseNDArray):
    """CSR matrix: data/indices/indptr (reference: ndarray.h kCSRStorage).
    Dense payload is LAZY; dot(csr, dense) runs O(nnz·k)."""

    def __init__(self, data, indices, indptr, shape, dtype=None):
        self._sp_data = data if isinstance(data, NDArray) \
            else NDArray(data, dtype=dtype)
        self._sp_indices = indices if isinstance(indices, NDArray) else \
            NDArray(np.asarray(indices, dtype=np.int64), dtype="int64")
        self._sp_indptr = indptr if isinstance(indptr, NDArray) else \
            NDArray(np.asarray(indptr, dtype=np.int64), dtype="int64")
        self._sp_shape = tuple(shape)
        # row id per nonzero (host, O(nnz), computed once)
        iptr = np.asarray(self._sp_indptr.asnumpy(), dtype=np.int64)
        self._sp_rows = NDArray(
            np.repeat(np.arange(shape[0], dtype=np.int64), np.diff(iptr)),
            dtype="int64")
        self._handle = object()
        self._ctx = None
        self._grad = None
        self._grad_req = "null"
        self._payload = None
        sp = self

        def densify():
            _mark_densified()
            dense = jnp.zeros(sp._sp_shape, sp._sp_data._data.dtype).at[
                sp._sp_rows._data.astype(jnp.int32),
                sp._sp_indices._data.astype(jnp.int32)].add(
                    sp._sp_data._data)
            sp._set_data(dense)

        self._set_lazy(densify, aval=jax.ShapeDtypeStruct(
            self._sp_shape, jnp.dtype(self._sp_data.dtype)))

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return self._sp_data

    @property
    def indices(self):
        return self._sp_indices

    @property
    def indptr(self):
        return self._sp_indptr

    def dot(self, dense):
        """CSR × dense → dense, O(nnz·k) gather/scatter-add (reference:
        tensor/dot-inl.h DotCsrDnsDns)."""
        d = dense._data if isinstance(dense, NDArray) else jnp.asarray(dense)
        return NDArray(_csr_dot(self._sp_data._data,
                                self._sp_rows._data.astype(jnp.int32),
                                self._sp_indices._data.astype(jnp.int32),
                                d, self._sp_shape[0]))

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data)
        raise MXNetError(f"cast {self.stype} -> {stype} unsupported")

    def todense(self):
        return NDArray(self._data)

    def __repr__(self):
        return (f"\n<CSRNDArray {self._sp_shape} "
                f"nnz={self._sp_data.shape[0]}>")


@functools.partial(jax.jit, static_argnums=(4,))
def _csr_dot(data, rows, cols, dense, n_rows):
    contrib = data[:, None] * dense[cols]              # (nnz, k)
    return jnp.zeros((n_rows, dense.shape[1]),
                     contrib.dtype).at[rows].add(contrib)


@jax.jit
def _dedup_rows_jit(vals, idx, oob):
    order = jnp.argsort(idx)
    sidx = idx[order]
    svals = vals[order]
    first = jnp.concatenate([jnp.array([True]), sidx[1:] != sidx[:-1]])
    slot = jnp.cumsum(first) - 1                        # unique slot per elt
    agg = jnp.zeros_like(svals).at[slot].add(svals)
    out_idx = jnp.full(idx.shape, oob, idx.dtype).at[slot].set(sidx)
    return agg, out_idx


def dedup_rows(values, indices, oob_index):
    """Aggregate duplicate row indices (jit-safe, static shapes).

    Returns (agg_values, dedup_indices) of the SAME nnz length where each
    unique row's summed values sit in its first slot and unused slots carry
    ``oob_index`` (dropped by scatters with mode='drop').  The reference's
    AddTakeGradRspKernel does the same sort-and-accumulate
    (src/operator/tensor/indexing_op.h)."""
    return _dedup_rows_jit(values, indices,
                           jnp.asarray(oob_index, indices.dtype))


def row_sparse_array(arg1, shape=None, dtype=None, ctx=None):
    if isinstance(arg1, (tuple, list)) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(data, indices, shape, dtype=dtype)
    # from dense
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    nz = np.where(np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(dense[nz], nz, dense.shape, dtype=dtype)


def csr_matrix(arg1, shape=None, dtype=None, ctx=None):
    if isinstance(arg1, (tuple, list)) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indices, indptr, shape, dtype=dtype)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    rows, cols = np.nonzero(dense)
    indptr = np.searchsorted(rows, np.arange(dense.shape[0] + 1))
    return CSRNDArray(dense[rows, cols], cols, indptr, dense.shape,
                      dtype=dtype)


def rand_sparse_ndarray(shape, stype, density=None, dtype=None,
                        rng=None):
    """Random sparse array + its dense numpy twin (reference:
    test_utils.py:254 — the sparse test-data generator)."""
    rng = rng or np.random
    density = 0.2 if density is None else density
    dtype = np.dtype(dtype or np.float32)
    if stype == "row_sparse":
        # density selects ROWS for row_sparse (reference semantics:
        # test_utils.py rand_sparse_ndarray row-wise generator)
        row_mask = rng.uniform(0, 1, shape[0]) < density
        dense = (rng.uniform(-1, 1, shape) *
                 row_mask.reshape((-1,) + (1,) * (len(shape) - 1))
                 ).astype(dtype)
        return row_sparse_array(dense), dense
    dense = (rng.uniform(-1, 1, shape) *
             (rng.uniform(0, 1, shape) < density)).astype(dtype)
    if stype == "csr":
        if len(shape) != 2:
            raise MXNetError("csr requires 2-D shape")
        return csr_matrix(dense), dense
    raise MXNetError(f"unknown sparse stype {stype!r}")


def cast_storage(arr, stype):
    """reference: tensor/cast_storage-inl.h"""
    if stype == "default":
        return NDArray(arr._data)
    dense = arr.asnumpy()
    if stype == "row_sparse":
        return row_sparse_array(NDArray(dense))
    if stype == "csr":
        return csr_matrix(NDArray(dense))
    raise MXNetError(stype)


def square_sum(arr, axis=None, keepdims=False):
    """O(nnz) sum-of-squares over a RowSparseNDArray (reference:
    src/operator/tensor/square_sum-inl.h — `_square_sum` FComputeEx on
    kRowSparseStorage).  Only the stored rows are touched; zero rows
    contribute nothing by construction.

    axis=1 returns a RowSparseNDArray sharing the input's row indices (the
    reference emits row_sparse output for the axis=1 case); axis=None or
    axis=0 returns a dense NDArray.
    """
    if not isinstance(arr, RowSparseNDArray):
        from . import _invoke
        return _invoke("_square_sum", [arr],
                       {"axis": axis, "keepdims": keepdims})
    vals = arr.data._data
    if isinstance(axis, (tuple, list)):
        axis = axis[0] if len(axis) == 1 else None
    if axis is None:
        return NDArray(jnp.sum(jnp.square(vals)))
    if len(arr._sp_shape) != 2:
        raise MXNetError(
            "square_sum with an axis supports 2-D row_sparse only "
            f"(got shape {arr._sp_shape}); use axis=None or densify")
    axis = axis % len(arr._sp_shape)
    if axis == 0:
        out = jnp.sum(jnp.square(vals), axis=0)
        if keepdims:
            out = out[None]
        return NDArray(out)
    # axis == 1: per-row sum over the stored rows only
    row = jnp.sum(jnp.square(vals.reshape(vals.shape[0], -1)), axis=1)
    out_shape = ((arr._sp_shape[0], 1) if keepdims
                 else (arr._sp_shape[0],))
    rvals = row[:, None] if keepdims else row
    return RowSparseNDArray(NDArray(rvals), arr.indices, out_shape)


def zeros(stype, shape, ctx=None, dtype=None):
    if stype == "row_sparse":
        return RowSparseNDArray(np.zeros((0,) + tuple(shape[1:]),
                                         dtype=dtype or np.float32),
                                np.zeros((0,)), shape, dtype=dtype)
    if stype == "csr":
        return CSRNDArray(np.zeros((0,), dtype=dtype or np.float32),
                          np.zeros((0,)), np.zeros(shape[0] + 1), shape,
                          dtype=dtype)
    raise MXNetError(stype)
