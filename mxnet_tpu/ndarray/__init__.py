"""mx.nd — imperative NDArray API (reference: python/mxnet/ndarray/)."""
from .ndarray import (NDArray, array, empty, zeros, ones, full, arange,
                      concatenate, stack_arrays, onehot_encode, moveaxis,
                      waitall, load, save, _invoke, _invoke_fn)
from .register import init_ndarray_module
from . import random  # noqa: F401
from . import linalg  # noqa: F401
from . import sparse  # noqa: F401

init_ndarray_module(globals())

# a few reference-API spellings not covered by the registry names
stack = globals().get("stack")


from ..base import ContribNamespace as _ContribNS
contrib = _ContribNS(globals())
