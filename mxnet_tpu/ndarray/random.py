"""mx.nd.random — sampling namespace (reference: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from .ndarray import _invoke, NDArray


def _shape_kw(shape):
    return () if shape is None else (shape if isinstance(shape, tuple) else (shape,)) \
        if not isinstance(shape, (list, tuple)) else tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    if isinstance(low, NDArray):
        return _invoke("_sample_uniform", [low, high],
                       {"shape": shape or (), "dtype": dtype}, out=out)
    return _invoke("_random_uniform", [],
                   {"low": low, "high": high, "shape": shape or (),
                    "dtype": dtype}, out=out)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    if isinstance(loc, NDArray):
        return _invoke("_sample_normal", [loc, scale],
                       {"shape": shape or (), "dtype": dtype}, out=out)
    return _invoke("_random_normal", [],
                   {"loc": loc, "scale": scale, "shape": shape or (),
                    "dtype": dtype}, out=out)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", **kw):
    return normal(loc, scale, tuple(shape), dtype)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    if isinstance(alpha, NDArray):
        return _invoke("_sample_gamma", [alpha, beta],
                       {"shape": shape or (), "dtype": dtype}, out=out)
    return _invoke("_random_gamma", [],
                   {"alpha": alpha, "beta": beta, "shape": shape or (),
                    "dtype": dtype}, out=out)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    if isinstance(scale, NDArray):
        return _invoke("_sample_exponential", [1.0 / scale],
                       {"shape": shape or (), "dtype": dtype}, out=out)
    return _invoke("_random_exponential", [],
                   {"lam": 1.0 / scale, "shape": shape or (), "dtype": dtype},
                   out=out)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    if isinstance(lam, NDArray):
        return _invoke("_sample_poisson", [lam],
                       {"shape": shape or (), "dtype": dtype}, out=out)
    return _invoke("_random_poisson", [],
                   {"lam": lam, "shape": shape or (), "dtype": dtype}, out=out)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None,
                      out=None, **kw):
    return _invoke("_random_negative_binomial", [],
                   {"k": k, "p": p, "shape": shape or (), "dtype": dtype},
                   out=out)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                  dtype="float32", ctx=None, out=None, **kw):
    return _invoke("_random_generalized_negative_binomial", [],
                   {"mu": mu, "alpha": alpha, "shape": shape or (),
                    "dtype": dtype}, out=out)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None, **kw):
    return _invoke("_random_randint", [],
                   {"low": low, "high": high, "shape": shape or (),
                    "dtype": dtype}, out=out)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    return _invoke("_sample_multinomial", [data],
                   {"shape": shape or (), "get_prob": get_prob, "dtype": dtype})


def shuffle(data, **kw):
    return _invoke("_shuffle", [data], {})
