"""Autogenerate the ``mx.nd.*`` namespace from the op registry.

TPU-native equivalent of the reference's import-time op-wrapper codegen
(python/mxnet/base.py:384 ``_init_op_module``,
python/mxnet/ndarray/register.py:29,156 ``_make_ndarray_function``): the
reference enumerates ops over the C API and exec's generated Python; here the
registry is already Python, so wrappers are closures — equally introspectable
via ``mx.nd.<op>.__doc__`` and ``list_ops()``.
"""
from __future__ import annotations

import numpy as np
import jax

from ..ops import registry as _reg
from .ndarray import NDArray, _invoke


def _is_tensor(x):
    return isinstance(x, (NDArray, np.ndarray, jax.Array))


def make_op_func(opdef: _reg.OpDef, name: str):
    def op_func(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)  # symbol-naming attr, meaningless eagerly
        if len(args) == 1 and isinstance(args[0], (list, tuple)) and opdef.variadic:
            args = tuple(args[0])
        if opdef.variadic:
            inputs = [a for a in args if a is not None]
            attrs = kwargs
        else:
            names = (opdef.arg_names or []) + (opdef.aux_names or [])
            supplied = {}
            for an in list(kwargs):
                if an in names and (_is_tensor(kwargs[an]) or kwargs[an] is None):
                    supplied[an] = kwargs.pop(an)
            pos = list(args)
            inputs = []
            for nm in names:
                if nm in supplied:
                    inputs.append(supplied[nm])
                elif pos:
                    inputs.append(pos.pop(0))
                else:
                    inputs.append(None)
            inputs.extend(pos)
            while inputs and inputs[-1] is None:
                inputs.pop()
            if any(i is None for i in inputs):
                # middle optional input (e.g. LeakyReLU gamma unused): replace
                # with a zero-size placeholder only if impl tolerates None —
                # pass through and let the impl default handle it.
                inputs = [i for i in inputs if i is not None]
            attrs = kwargs
        return _invoke(opdef.name, inputs, attrs, out=out)

    op_func.__name__ = name
    op_func.__doc__ = _reg.build_op_doc(opdef, name, flavor="nd")
    return op_func


def init_ndarray_module(namespace: dict):
    for name in _reg.list_ops():
        opdef = _reg.get(name)
        namespace.setdefault(name, make_op_func(opdef, name))
