"""mx.nd.linalg (reference: python/mxnet/ndarray/linalg.py over la_op.cc)."""
from .ndarray import _invoke


def _make(name, op):
    def f(*args, **kw):
        out = kw.pop("out", None)
        return _invoke(op, list(args), kw, out=out)
    f.__name__ = name
    return f


gemm = _make("gemm", "linalg_gemm")
gemm2 = _make("gemm2", "linalg_gemm2")
potrf = _make("potrf", "linalg_potrf")
potri = _make("potri", "linalg_potri")
trmm = _make("trmm", "linalg_trmm")
trsm = _make("trsm", "linalg_trsm")
syrk = _make("syrk", "linalg_syrk")
gelqf = _make("gelqf", "linalg_gelqf")
sumlogdiag = _make("sumlogdiag", "linalg_sumlogdiag")
syevd = _make("syevd", "linalg_syevd")
inverse = _make("inverse", "linalg_inverse")
det = _make("det", "linalg_det")
slogdet = _make("slogdet", "linalg_slogdet")
