"""NDArray: the imperative tensor.

TPU-native equivalent of the reference NDArray (include/mxnet/ndarray.h:69,
src/ndarray/ndarray.cc) and the imperative dispatcher
(src/imperative/imperative.cc Invoke/InvokeOp, imperative_utils.h:82-341).

Design: an NDArray is a *mutable handle* over an immutable ``jax.Array``.
The reference's engine-var read/write dependency system
(threaded_engine.h:112-214) is replaced by two facts about JAX/XLA:
 (1) dispatch is already async — ops return futures (jax.Array) immediately
     and ``wait_to_read`` is ``block_until_ready``;
 (2) values are immutable, so "mutation" = swapping the handle's payload and
     issuing a fresh identity token (``_handle``) used by the autograd tape
     for versioning.
"""
from __future__ import annotations

import numbers
from contextlib import nullcontext as _nullcontext
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError, env
from ..context import Context, current_context, cpu
from .. import autograd as _ag
from .. import profiler as _prof
from .. import random as _rnd
from ..ops import registry as _reg


def _default_dtype():
    return np.dtype(env("MXNET_DEFAULT_DTYPE", "float32"))


class NDArray:
    __slots__ = ("_payload", "_thunk", "_handle", "_ctx", "_grad",
                 "_grad_req", "_deferred_init", "__weakref__")
    # make NumPy defer to our reflected operators (a + nd works)
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        self._thunk = None
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = np.asarray(data)
            if dtype is None and data.dtype == np.float64:
                dtype = _default_dtype()
            if dtype is not None:
                data = data.astype(dtype)
            if ctx is not None:
                data = jax.device_put(data, ctx.jax_device())
            else:
                data = jnp.asarray(data)
        elif dtype is not None and data.dtype != jnp.dtype(dtype):
            data = data.astype(jnp.dtype(dtype))
        self._payload = data
        self._handle = object()
        self._ctx = ctx
        self._grad = None
        self._grad_req = "null"

    # -- lazy payload (engine-style deferred execution) ---------------------
    # An executor may hand out output handles whose value is produced by a
    # not-yet-dispatched fused XLA program (reference analog: engine vars
    # whose value exists only after the pushed opr completes).  Reading
    # ``_data`` forces the producer; ``_set_data`` fulfils it.
    @property
    def _data(self):
        if self._thunk is not None:
            thunk, self._thunk = self._thunk, None
            thunk()  # expected to _set_data on this (and sibling) arrays
        return self._payload

    @_data.setter
    def _data(self, value):
        self._payload = value
        self._thunk = None

    def _set_lazy(self, thunk, aval=None):
        self._thunk = thunk
        if aval is not None:
            self._payload = aval  # ShapeDtypeStruct placeholder for .shape

    # -- engine sync points (reference: NDArray::WaitToRead/WaitToWrite) ----
    def wait_to_read(self):
        _prof.record_host_sync("ndarray.wait_to_read")
        self._data.block_until_ready()
        return self

    wait_to_write = wait_to_read

    # -- basic properties (read the placeholder aval, never force) ----------
    @property
    def shape(self):
        return tuple(self._payload.shape)

    @property
    def dtype(self):
        return np.dtype(str(self._payload.dtype)) \
            if self._payload.dtype != jnp.bfloat16 else self._payload.dtype

    @property
    def size(self):
        return int(np.prod(self._payload.shape)) if self._payload.shape else 1

    @property
    def ndim(self):
        return len(self._payload.shape)

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        try:
            dev = list(self._payload.devices())[0]
            return Context("cpu" if dev.platform == "cpu" else "tpu", dev.id)
        except Exception:
            return cpu()

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return self.transpose()

    @property
    def grad(self):
        return self._grad

    # -- conversions --------------------------------------------------------
    def asnumpy(self) -> np.ndarray:
        # every asnumpy is a host-blocking device readback — the thing the
        # sync-free training loop exists to avoid (profiler.host_syncs is
        # the regression gate; see metric.EvalMetric.sync)
        _prof.record_host_sync("ndarray.asnumpy")
        data = self._data
        if (hasattr(data, "sharding")
                and not getattr(data, "is_fully_addressable", True)):
            # global array from a multi-process SPMD mesh: gather the
            # non-addressable shards over the coordination backend (the
            # analog of the reference's kvstore pull to host)
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(data, tiled=True))
        return np.asarray(data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kw):
        return self._data.__dlpack__(**kw)

    def astype(self, dtype, copy=True):
        return _invoke("Cast", [self], {"dtype": np.dtype(dtype).name
                                        if dtype is not jnp.bfloat16 else "bfloat16"})

    def copy(self):
        return _invoke("_copy", [self], {})

    def copyto(self, other):
        """reference: NDArray::CopyFromTo (ndarray.cc:513)."""
        if isinstance(other, NDArray):
            other._set_data(jax.device_put(self._data,
                                           other.context.jax_device()))
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()),
                           ctx=other)
        raise TypeError(type(other))

    def as_in_context(self, context: Context):
        if context == self.context:
            return self
        return NDArray(jax.device_put(self._data, context.jax_device()),
                       ctx=context)

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import cast_storage
        return cast_storage(self, stype)

    def detach(self):
        out = NDArray(self._data)
        return out

    # -- autograd -----------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """reference: ndarray.py attach_grad → MXAutogradMarkVariables.

        ``stype='row_sparse'`` requests a row_sparse gradient: autograd
        will produce values+indices for only the touched rows (supported
        when this array is consumed via Embedding/take — the reference's
        sparse-grad ops) instead of a dense (shape) gradient."""
        if stype == "row_sparse":
            from .sparse import zeros as sp_zeros
            self._grad = sp_zeros("row_sparse", self.shape,
                                  dtype=self._data.dtype)
        else:
            self._grad = zeros(self.shape, dtype=self._data.dtype)
        self._grad_req = grad_req

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _ag.backward([self], [out_grad] if out_grad is not None else None,
                     retain_graph=retain_graph, train_mode=train_mode)

    # -- mutation (engine write-dependency equivalent) ----------------------
    def _set_data(self, value):
        self._data = value
        self._handle = object()  # new version token

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        if isinstance(key, NDArray):
            key = key._data
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            v = jnp.broadcast_to(jnp.asarray(value, self._data.dtype),
                                 self.shape)
            self._set_data(jnp.asarray(v))
            return
        self._set_data(self._data.at[key].set(
            value if not isinstance(value, np.ndarray) else jnp.asarray(value)))

    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key._data.astype(jnp.int32)
        if isinstance(key, numbers.Integral):
            return _invoke_fn(lambda d, **kw: d[int(key)], [self], {})
        return _invoke_fn(lambda d, **kw: d[key], [self], {})

    # -- python protocol ----------------------------------------------------
    def __len__(self):
        return self.shape[0] if self.ndim else 0

    def __bool__(self):
        if self.size != 1:
            raise ValueError("ambiguous truth value of multi-element NDArray")
        return bool(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self.shape))} " \
               f"@{self.context}>"

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- arithmetic (routed through the op registry so autograd sees them) --
    def _binop(self, other, op, scalar_op, rop=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if rop else (self, other)
            return _invoke(op, [a, b], {})
        if isinstance(other, numbers.Number):
            return _invoke(scalar_op, [self], {"scalar": float(other)})
        if isinstance(other, np.ndarray):
            a = NDArray(other)
            a2, b = (a, self) if rop else (self, a)
            return _invoke(op, [a2, b], {})
        return NotImplemented

    def __add__(self, o): return self._binop(o, "broadcast_add", "_plus_scalar")
    __radd__ = __add__
    def __sub__(self, o): return self._binop(o, "broadcast_sub", "_minus_scalar")
    def __rsub__(self, o): return self._binop(o, "broadcast_sub", "_rminus_scalar", rop=True)
    def __mul__(self, o): return self._binop(o, "broadcast_mul", "_mul_scalar")
    __rmul__ = __mul__
    def __truediv__(self, o): return self._binop(o, "broadcast_div", "_div_scalar")
    def __rtruediv__(self, o): return self._binop(o, "broadcast_div", "_rdiv_scalar", rop=True)
    __div__ = __truediv__
    __rdiv__ = __rtruediv__
    def __mod__(self, o): return self._binop(o, "broadcast_mod", "_mod_scalar")
    def __rmod__(self, o): return self._binop(o, "broadcast_mod", "_rmod_scalar", rop=True)
    def __pow__(self, o): return self._binop(o, "broadcast_power", "_power_scalar")
    def __rpow__(self, o): return self._binop(o, "broadcast_power", "_rpower_scalar", rop=True)
    def __neg__(self): return _invoke("negative", [self], {})
    def __abs__(self): return _invoke("abs", [self], {})
    def __matmul__(self, o): return _invoke("dot", [self, o], {})

    def __eq__(self, o): return self._binop(o, "broadcast_equal", "_equal_scalar")
    def __ne__(self, o): return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")
    def __gt__(self, o): return self._binop(o, "broadcast_greater", "_greater_scalar")
    def __ge__(self, o): return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")
    def __lt__(self, o): return self._binop(o, "broadcast_lesser", "_lesser_scalar")
    def __le__(self, o): return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # in-place: swap payload (reference: engine write dep on same var)
    def __iadd__(self, o):
        out = self.__add__(o)
        self._set_data(out._data)
        return self

    def __isub__(self, o):
        out = self.__sub__(o)
        self._set_data(out._data)
        return self

    def __imul__(self, o):
        out = self.__mul__(o)
        self._set_data(out._data)
        return self

    def __itruediv__(self, o):
        out = self.__truediv__(o)
        self._set_data(out._data)
        return self

    # -- method versions of common ops -------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return _invoke("Reshape", [self], {"shape": shape, **kwargs})

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return _invoke("transpose", [self], {"axes": axes})

    def flatten(self):
        return _invoke("Flatten", [self], {})

    def expand_dims(self, axis):
        return _invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return _invoke("squeeze", [self], {"axis": axis})

    def broadcast_to(self, shape):
        return _invoke("broadcast_to", [self], {"shape": shape})

    def slice(self, begin, end, step=()):
        return _invoke("slice", [self], {"begin": begin, "end": end, "step": step})

    def slice_axis(self, axis, begin, end):
        return _invoke("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return _invoke("take", [self, indices], {"axis": axis, "mode": mode})

    def pick(self, index, axis=-1, keepdims=False):
        return _invoke("pick", [self, index], {"axis": axis, "keepdims": keepdims})

    def one_hot(self, depth, **kw):
        return _invoke("one_hot", [self], {"depth": depth, **kw})

    def clip(self, a_min, a_max):
        return _invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def abs(self): return _invoke("abs", [self], {})
    def sign(self): return _invoke("sign", [self], {})
    def sqrt(self): return _invoke("sqrt", [self], {})
    def square(self): return _invoke("square", [self], {})
    def exp(self): return _invoke("exp", [self], {})
    def log(self): return _invoke("log", [self], {})
    def tanh(self): return _invoke("tanh", [self], {})
    def sigmoid(self): return _invoke("sigmoid", [self], {})
    def relu(self): return _invoke("relu", [self], {})
    def softmax(self, axis=-1): return _invoke("softmax", [self], {"axis": axis})
    def log_softmax(self, axis=-1): return _invoke("log_softmax", [self], {"axis": axis})

    def _reduce(self, name, axis=None, keepdims=False, **kw):
        return _invoke(name, [self], {"axis": axis, "keepdims": keepdims, **kw})

    def sum(self, axis=None, keepdims=False):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce("mean", axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce("prod", axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce("min", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return _invoke("norm", [self], {"ord": ord, "axis": axis,
                                        "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return _invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return _invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return _invoke("argsort", [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return _invoke("topk", [self], {"axis": axis, "k": k,
                                        "ret_typ": ret_typ, "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return _invoke("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def swapaxes(self, dim1, dim2):
        return _invoke("SwapAxis", [self], {"dim1": dim1, "dim2": dim2})

    def flip(self, axis):
        return _invoke("flip", [self], {"axis": axis})

    def tile(self, reps):
        return _invoke("tile", [self], {"reps": reps})

    def repeat(self, repeats, axis=None):
        return _invoke("repeat", [self], {"repeats": repeats, "axis": axis})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _invoke("SliceChannel", [self],
                       {"num_outputs": num_outputs, "axis": axis,
                        "squeeze_axis": squeeze_axis})

    def dot(self, other, **kw):
        return _invoke("dot", [self, other], kw)


# ===========================================================================
# The imperative dispatcher (reference: Imperative::Invoke, imperative.cc:86)
# ===========================================================================
def _naive_mode():
    return env("MXNET_ENGINE_TYPE", "Async") == "NaiveEngine"


def _invoke_fn(fn, inputs: Sequence[NDArray], attrs, n_out: Optional[int] = None,
               rng_key=None, out=None, n_keep=None):
    """Low-level: run pure fn over input payloads, wrap, record on tape."""
    vals = [x._data for x in inputs]
    if rng_key is not None:
        outs = fn(rng_key, *vals, **attrs)
    else:
        outs = fn(*vals, **attrs)
    single = not isinstance(outs, (tuple, list))
    if single:
        outs = (outs,)
    keep = n_keep if n_keep is not None else len(outs)
    visible = outs[:keep]
    if out is not None:
        out_arrays = [out] if isinstance(out, NDArray) else list(out)
        for oa, v in zip(out_arrays, visible):
            oa._set_data(v)
    else:
        out_arrays = [NDArray(v) for v in visible]
    if _ag.is_recording():
        _ag._record(fn, dict(attrs), list(inputs), vals, out_arrays,
                    rng_key=rng_key, n_keep=keep)
    if _naive_mode():
        for oa in out_arrays:
            oa._data.block_until_ready()
    if single or len(out_arrays) == 1:
        return out_arrays[0]
    return out_arrays


def _invoke(op_name: str, inputs, attrs, out=None):
    """Dispatch a registered op imperatively (handles rng/aux/is_train)."""
    opdef = _reg.get(op_name)
    _reg.record_execution(op_name)
    inputs = [x if isinstance(x, NDArray) else NDArray(x) for x in inputs]
    attrs = {k: v for k, v in attrs.items() if v is not None or k in ("axis",)}
    kwargs = dict(attrs)
    is_train = _ag.is_training()
    if opdef.takes_is_train:
        kwargs["is_train"] = is_train
    rng_key = _rnd.next_key() if opdef.needs_rng else None

    n_aux_updates = 0
    if opdef.num_aux and opdef.takes_is_train and is_train:
        n_aux_updates = opdef.num_aux

    vals = [x._data for x in inputs]
    fn = opdef.fn
    from .. import profiler as _prof
    with _prof.scope(opdef.name, require_mode="all"):
        if rng_key is not None:
            outs = fn(rng_key, *vals, **kwargs)
        else:
            outs = fn(*vals, **kwargs)
    single = not isinstance(outs, (tuple, list))
    if single:
        outs = (outs,)

    # aux writeback (BatchNorm moving stats): trailing outputs -> aux inputs
    if n_aux_updates:
        aux_arrays = inputs[-opdef.num_aux:]
        for aa, v in zip(aux_arrays, outs[-n_aux_updates:]):
            aa._set_data(v)
        outs = outs[:-n_aux_updates]

    nvis = getattr(opdef, "num_visible", None)
    if callable(nvis):  # attr-dependent (reference NumVisibleOutputs)
        nvis = nvis(attrs)
    keep = len(outs)
    if out is not None:
        out_arrays = [out] if isinstance(out, NDArray) else list(out)
        for oa, v in zip(out_arrays, outs[:len(out_arrays)]):
            oa._set_data(v)
    else:
        out_arrays = [NDArray(v) for v in outs]

    if _ag.is_recording():
        # the recorded closure hides aux-update outputs; n_keep maps the
        # visible outputs only
        def pure(*a, _fn=fn, _kw=kwargs, _n=n_aux_updates, **_ignored):
            r = _fn(*a, **_kw)
            if not isinstance(r, (tuple, list)):
                r = (r,)
            return tuple(r[:len(r) - _n] if _n else r)
        _ag._record(pure, dict(attrs), list(inputs), vals, out_arrays,
                    rng_key=rng_key, n_keep=keep, op_name=opdef.name)

    if _naive_mode():
        for oa in out_arrays:
            oa._data.block_until_ready()

    if nvis is not None and nvis < len(out_arrays):
        out_arrays = out_arrays[:nvis]
    return out_arrays[0] if len(out_arrays) == 1 else out_arrays


# ===========================================================================
# creation / free functions (reference: python/mxnet/ndarray/ndarray.py tail)
# ===========================================================================
def array(source_array, ctx=None, dtype=None) -> NDArray:
    if dtype is None and not hasattr(source_array, "dtype"):
        # reference semantics (ndarray.py array): python lists/scalars
        # default to float32; arrays keep their dtype
        dtype = np.float32
    return NDArray(source_array, ctx=ctx or current_context(), dtype=dtype)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kw):
    if isinstance(shape, numbers.Integral):
        shape = (shape,)
    dtype = np.dtype(dtype).name if dtype is not None and dtype is not jnp.bfloat16 \
        else ("bfloat16" if dtype is jnp.bfloat16 else "float32")
    out = _invoke("_zeros", [], {"shape": tuple(shape), "dtype": dtype})
    if ctx is not None:
        out._set_data(jax.device_put(out._data, ctx.jax_device()))
    return out


def ones(shape, ctx=None, dtype=None, **kw):
    if isinstance(shape, numbers.Integral):
        shape = (shape,)
    dtype = np.dtype(dtype).name if dtype is not None else "float32"
    out = _invoke("_ones", [], {"shape": tuple(shape), "dtype": dtype})
    if ctx is not None:
        out._set_data(jax.device_put(out._data, ctx.jax_device()))
    return out


def full(shape, val, ctx=None, dtype=None, **kw):
    if isinstance(shape, numbers.Integral):
        shape = (shape,)
    dtype = np.dtype(dtype).name if dtype is not None else "float32"
    return _invoke("_full", [], {"shape": tuple(shape), "dtype": dtype,
                                 "value": float(val)})


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    dtype = np.dtype(dtype).name if dtype is not None else "float32"
    return _invoke("_arange", [], {"start": start, "stop": stop, "step": step,
                                   "repeat": repeat, "dtype": dtype})


def concatenate(arrays, axis=0, always_copy=True):
    return _invoke("Concat", list(arrays), {"dim": axis})


def stack_arrays(arrays, axis=0):
    return _invoke("stack", list(arrays), {"axis": axis})


def onehot_encode(indices, out):
    depth = out.shape[1]
    res = _invoke("one_hot", [indices], {"depth": depth})
    out._set_data(res._data)
    return out


def moveaxis(tensor, source, destination):
    return _invoke_fn(lambda d, **kw: jnp.moveaxis(d, source, destination),
                      [tensor], {})


def waitall():
    """reference: Engine::WaitForAll — drain all async work."""
    import jax as _jax
    try:
        _jax.effects_barrier()
    except Exception:
        pass


def load(fname):
    from ..serialization import load_ndarrays
    return load_ndarrays(fname)


def save(fname, data):
    from ..serialization import save_ndarrays
    save_ndarrays(fname, data)
