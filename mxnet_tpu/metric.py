"""Evaluation metrics (reference: python/mxnet/metric.py).

Metric math runs in numpy on host — metrics consume already-computed outputs
and must not trigger recompilation; the device stays busy with the next
jitted step while the host scores the previous one.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy
import numpy as np  # shadowed below by metric.np(); use `numpy` internally

from .base import MXNetError, Registry
from .ndarray import NDArray

_METRIC_REGISTRY = Registry("metric")


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            f"Shape of labels {label_shape} does not match shape of "
            f"predictions {pred_shape}")


def _np(x):
    return x.asnumpy() if isinstance(x, NDArray) else numpy.asarray(x)


class EvalMetric:
    """Base metric (reference: metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def get_config(self):
        config = self._kwargs.copy()
        config.update({
            'metric': self.__class__.__name__,
            'name': self.name,
            'output_names': self.output_names,
            'label_names': self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


register = _METRIC_REGISTRY.register


def create(metric, *args, **kwargs):
    """reference: metric.py create."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite_metric = CompositeEvalMetric()
        for child_metric in metric:
            composite_metric.add(create(child_metric, *args, **kwargs))
        return composite_metric
    return _METRIC_REGISTRY.get(metric)(*args, **kwargs)


@register
class CompositeEvalMetric(EvalMetric):
    """reference: metric.py CompositeEvalMetric."""

    def __init__(self, metrics=None, name='composite',
                 output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)
        if metrics is None:
            metrics = []
        self.metrics = [create(i) for i in metrics]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError(f"Metric index {index} is out of range 0 and "
                              f"{len(self.metrics)}")

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, numpy.generic)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


@register
class Accuracy(EvalMetric):
    """reference: metric.py Accuracy."""

    def __init__(self, axis=1, name='accuracy',
                 output_names=None, label_names=None):
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred_label = _np(pred_label)
            label = _np(label)
            if pred_label.shape != label.shape:
                pred_label = numpy.argmax(pred_label, axis=self.axis)
            pred_label = pred_label.astype('int32').flatten()
            label = label.astype('int32').flatten()
            check_label_shapes(label, pred_label, shape=1)
            self.sum_metric += (pred_label == label).sum()
            self.num_inst += len(pred_label)


@register
class TopKAccuracy(EvalMetric):
    """reference: metric.py TopKAccuracy."""

    def __init__(self, top_k=1, name='top_k_accuracy',
                 output_names=None, label_names=None):
        super().__init__(name, top_k=top_k, output_names=output_names,
                         label_names=label_names)
        self.top_k = top_k
        assert self.top_k > 1, 'Please use Accuracy if top_k is no more than 1'
        self.name += '_%d' % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            assert len(pred.shape) <= 2, \
                'Predictions should be no more than 2 dims'
            pred = _np(pred).astype('float32')
            label = _np(label).astype('int32').ravel()
            check_label_shapes(label, pred)
            if pred.ndim == 1:
                self.sum_metric += int((pred.astype('int32') == label)
                                       .sum())
            else:
                k = min(pred.shape[1], self.top_k)
                # top-k SET membership: argpartition selects the k
                # largest in O(n) (no full sort needed — the k columns
                # are checked as a set anyway)
                top = numpy.argpartition(pred, -k, axis=1)[:, -k:]
                self.sum_metric += int(
                    (top == label[:, None]).any(axis=1).sum())
            self.num_inst += pred.shape[0]


@register
class F1(EvalMetric):
    """Binary-classification F1 (reference: metric.py F1)."""

    def __init__(self, name='f1', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _np(pred)
            # ravel BEFORE the vectorized compares: an (n,1) label would
            # broadcast against the (n,) argmax into an (n,n) matrix
            label = _np(label).astype('int32').ravel()
            pred_label = numpy.argmax(pred, axis=1)
            check_label_shapes(label, pred)
            if len(numpy.unique(label)) > 2:
                raise ValueError(
                    "F1 currently only supports binary classification.")
            # vectorized confusion counts; 2*tp/(2*tp+fp+fn) is the
            # precision/recall harmonic mean with the 0/0 -> 0 convention
            tp = float(((pred_label == 1) & (label == 1)).sum())
            fp = float(((pred_label == 1) & (label == 0)).sum())
            fn = float(((pred_label == 0) & (label == 1)).sum())
            denom = 2 * tp + fp + fn
            self.sum_metric += (2 * tp / denom) if denom > 0 else 0.
            self.num_inst += 1


@register
class Perplexity(EvalMetric):
    """reference: metric.py Perplexity."""

    def __init__(self, ignore_label, axis=-1, name='perplexity',
                 output_names=None, label_names=None):
        super().__init__(name, ignore_label=ignore_label, axis=axis,
                         output_names=output_names, label_names=label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.
        num = 0
        for label, pred in zip(labels, preds):
            label = _np(label)
            pred = _np(pred)
            assert label.size == pred.size / pred.shape[-1], \
                f"shape mismatch: {label.shape} vs. {pred.shape}"
            label = label.reshape((label.size,)).astype('int32')
            probs = numpy.take_along_axis(
                pred.reshape(-1, pred.shape[-1]), label[:, None],
                axis=-1).squeeze(-1)
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(probs.dtype)
                num -= int(ignore.sum())
                probs = probs * (1 - ignore) + ignore
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += probs.size
        # accumulate total loss/count; get() exponentiates the GLOBAL mean
        # (reference: metric.py Perplexity.get)
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, float(numpy.exp(self.sum_metric / self.num_inst)))


class _RegressionMetric(EvalMetric):
    """Shared per-batch regression scoring: subclasses define the batch
    score over the residual; the mean-of-batch-scores accumulation (one
    num_inst per batch) is the reference contract for all three."""

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _np(label)
            pred = _np(pred)
            if label.ndim == 1:
                label = label[:, None]
            if pred.ndim == 1:
                pred = pred[:, None]
            self.sum_metric += self._score(label - pred)
            self.num_inst += 1


@register
class MAE(_RegressionMetric):
    """reference: metric.py MAE."""

    def __init__(self, name='mae', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    @staticmethod
    def _score(err):
        return numpy.abs(err).mean()


@register
class MSE(_RegressionMetric):
    """reference: metric.py MSE."""

    def __init__(self, name='mse', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    @staticmethod
    def _score(err):
        return (err ** 2.0).mean()


@register
class RMSE(_RegressionMetric):
    """reference: metric.py RMSE."""

    def __init__(self, name='rmse', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    @staticmethod
    def _score(err):
        return numpy.sqrt((err ** 2.0).mean())


@register
class CrossEntropy(EvalMetric):
    """reference: metric.py CrossEntropy."""

    def __init__(self, eps=1e-12, name='cross-entropy',
                 output_names=None, label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _np(label)
            pred = _np(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    """reference: metric.py NegativeLogLikelihood — same per-example
    -log p[label] accumulation as CrossEntropy, under its NLL name."""

    def __init__(self, eps=1e-12, name='nll-loss',
                 output_names=None, label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


@register
class PearsonCorrelation(EvalMetric):
    """reference: metric.py PearsonCorrelation."""

    def __init__(self, name='pearsonr', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            check_label_shapes(label, pred, 1)
            label = _np(label).ravel()
            pred = _np(pred).ravel()
            self.sum_metric += numpy.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of a loss-valued output (reference: metric.py Loss)."""

    def __init__(self, name='loss', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, _, preds):
        for pred in preds:
            self.sum_metric += float(_np(pred).sum())
            self.num_inst += _np(pred).size if not numpy.isscalar(pred) else 1


@register
class Torch(Loss):
    """reference: metric.py Torch (alias of Loss with torch name)."""

    def __init__(self, name='torch', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name='caffe', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wrap feval(label, pred) (reference: metric.py CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find('<') != -1:
                name = 'custom(%s)' % name
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = _np(label)
            pred = _np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


_METRIC_REGISTRY.alias('acc', 'accuracy')
_METRIC_REGISTRY.alias('top_k_acc', 'topkaccuracy')
_METRIC_REGISTRY.alias('top_k_accuracy', 'topkaccuracy')
_METRIC_REGISTRY.alias('ce', 'crossentropy')
_METRIC_REGISTRY.alias('cross-entropy', 'crossentropy')
_METRIC_REGISTRY.alias('nll_loss', 'negativeloglikelihood')
_METRIC_REGISTRY.alias('pearsonr', 'pearsoncorrelation')


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """reference: metric.py np — wrap a numpy feval as a metric factory."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
