"""Evaluation metrics (reference: python/mxnet/metric.py).

Two accumulation paths:

* **host path** (``update``/``update_dict``): numpy on host, one
  device->host readback per batch — the classic reference contract, kept
  bit-compatible for custom metrics and direct callers.
* **device path** (``device_update``/``update_device``/``sync``): pure
  jax ops over a ``(sum_metric, num_inst)`` pytree state that stays ON
  the async engine.  The training/eval loops accumulate through
  ``accumulate_dict`` (device when possible), and the host counters only
  see the state at ``sync()`` — ONE readback per log interval instead of
  one (or three) per step.  This is the MXNet paper's "everything stays
  on the async engine" discipline applied to scoring: per-batch
  ``EvalMetric.update`` readbacks were the last host serialization in
  ``fit``/``score`` (docs/PERF_NOTES.md round 8).

``device_update`` is functional (state in, state out) so the same math
rides a ``lax.scan`` carry: ``Module.run_steps`` folds K steps of
metrics into the one scanned program with zero extra dispatches.
"""
from __future__ import annotations

import logging
import math
from typing import List, Optional, Sequence

import numpy
import numpy as np  # shadowed below by metric.np(); use `numpy` internally

from .base import MXNetError, Registry, env
from .ndarray import NDArray

_METRIC_REGISTRY = Registry("metric")

# jitted per-batch device folds, keyed by EvalMetric._device_sig —
# shared across metric INSTANCES (see _device_update_jitted)
_DEVICE_JIT_CACHE: dict = {}


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            f"Shape of labels {label_shape} does not match shape of "
            f"predictions {pred_shape}")


def _np(x):
    # analysis: allow(host-sync): legacy host-metric fallback path (one sync per batch BY DESIGN, pinned >=N by test_sync_free); NDArray.asnumpy records itself
    return x.asnumpy() if isinstance(x, NDArray) else numpy.asarray(x)


class EvalMetric:
    """Base metric (reference: metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def get_config(self):
        config = self._kwargs.copy()
        config.update({
            'metric': self.__class__.__name__,
            'name': self.name,
            'output_names': self.output_names,
            'label_names': self.label_names})
        return config

    def _select_dict(self, label, pred):
        """output_names/label_names selection shared by the host
        (update_dict) and device (device_update_dict) entry points."""
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        return label, pred

    def update_dict(self, label, pred):
        label, pred = self._select_dict(label, pred)
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    # -- device-resident accumulation ---------------------------------------
    # Converted metrics set ``device_capable`` and implement
    # ``device_update`` as pure jax ops; everything else (custom metrics,
    # Pearson) keeps the host path and the loops fall back with a
    # one-time warning.  State default: scalar (sum_metric f32,
    # num_inst i32) — shapes/dtypes must stay FIXED across updates
    # because the state rides lax.scan carries (Module.run_steps).
    device_capable = False
    _device_state = None   # class default so subclasses never AttributeError

    def device_init(self):
        """Zero accumulation state for the device path."""
        import jax.numpy as jnp
        return (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32))

    def device_update(self, state, labels, preds):
        """Functional device update: fold one batch of already-on-device
        ``labels``/``preds`` (lists of jax arrays) into ``state`` and
        return the new state.  Pure — jit/scan-traceable, no
        data-dependent host control flow, no readbacks.

        Subclasses: any hyperparameter this reads must flow through
        ``EvalMetric.__init__(**kwargs)`` — compiled folds are cached by
        ``_device_sig()``, which only sees those kwargs."""
        raise NotImplementedError(
            f"{type(self).__name__} has no device form")

    def device_update_dict(self, state, label, pred):
        """``update_dict`` in functional device form (the shape
        Module.run_steps folds into its scan body)."""
        label, pred = self._select_dict(label, pred)
        return self.device_update(state, label, pred)

    @staticmethod
    def _as_device(x):
        import jax.numpy as jnp
        return x._data if isinstance(x, NDArray) else jnp.asarray(x)

    def update_device(self, labels, preds):
        """Stateful device-resident update (the sync-free analog of
        ``update``): accumulation is buffered on the async engine;
        nothing crosses to the host until ``sync()``.

        The whole per-batch fold dispatches as ONE jitted program
        (cached per input shapes), not one eager op at a time — a
        per-batch metric costs a single async dispatch, the same
        discipline as the fused training step."""
        labels = [self._as_device(x) for x in labels]
        preds = [self._as_device(x) for x in preds]
        st = self._device_state if self._device_state is not None \
            else self.device_init()
        self._device_state = self._device_update_jitted()(st, labels,
                                                          preds)

    def _device_kwargs_shareable(self):
        """True when every hyperparameter kwarg is primitive — i.e. the
        signature fully determines the traced math and a compiled fold
        may be shared across instances."""
        return all(isinstance(v, (int, float, str, bool, type(None)))
                   for v in self._kwargs.values())

    def _device_update_jitted(self, dict_form=False):
        """Jitted device_update shared ACROSS instances with the same
        _device_sig (every fit()/score() creates fresh metrics — a
        per-instance jit would retrace the fold per call site; the
        signature key makes Accuracy compile once per shape, globally).
        Metrics with non-primitive hyperparameters keep their jit on
        the INSTANCE instead: the global cache stays bounded by the set
        of distinct primitive configs, never growing per instance.
        ``dict_form`` jits :meth:`device_update_dict` instead (name
        selection runs at trace time) — the composite fold uses it so
        every child's selection rides the same one program."""
        def _make():
            import jax
            return jax.jit(
                lambda st, l, p, m=self, d=dict_form:
                (m.device_update_dict if d else m.device_update)(st, l, p))
        if not self._device_kwargs_shareable():
            attr = "_device_jit_dict" if dict_form else "_device_jit"
            fn = self.__dict__.get(attr)
            if fn is None:
                fn = _make()
                setattr(self, attr, fn)
            return fn
        key = (self._device_sig(), dict_form)
        fn = _DEVICE_JIT_CACHE.get(key)
        if fn is None:
            # closing over THIS instance is safe: an equal signature
            # means equal hyperparameters, hence identical traced math
            fn = _DEVICE_JIT_CACHE[key] = _make()
        return fn

    def device_enabled(self):
        """THE enablement rule for device-resident accumulation —
        the single predicate shared by accumulate/accumulate_dict and
        the fused drivers (Module.run_steps, Trainer.step_k), so the
        ``MXNET_DEVICE_METRICS`` kill-switch contract can never diverge
        between the eager loops and the scanned ones."""
        return self.device_capable and env("MXNET_DEVICE_METRICS", True)

    def accumulate(self, labels, preds):
        """``update``, minus the per-batch host sync: routes to the
        device form when available (and ``MXNET_DEVICE_METRICS`` isn't
        0), else falls back to the classic host update with a one-time
        warning.  The framework training/eval loops accumulate through
        this (and :meth:`accumulate_dict`)."""
        if self.device_enabled():
            self.update_device(labels, preds)
            return
        self._warn_host_fallback()
        self.update(labels, preds)

    def accumulate_dict(self, label, pred):
        """``update_dict`` without the per-batch host sync (see
        :meth:`accumulate`)."""
        if self.device_enabled():
            label, pred = self._select_dict(label, pred)
            self.update_device(label, pred)
            return
        self._warn_host_fallback()
        self.update_dict(label, pred)

    def _warn_host_fallback(self):
        if not env("MXNET_DEVICE_METRICS", True):
            return   # explicitly disabled: per-batch syncs are intentional
        if getattr(self, "_host_sync_warned", False):
            return
        self._host_sync_warned = True
        logging.warning(
            "metric %r has no device form: accumulating on host costs one "
            "device->host sync per batch (implement device_update()/"
            "device_init() to keep the training loop sync-free)", self.name)

    def sync(self, state=None):
        """Fold device-resident accumulation into the classic host
        counters with ONE device->host readback (counted by
        profiler.record_host_sync).  Without ``state`` this drains the
        pending internal state from update_device; with ``state`` it
        folds an external functional state (a scan carry).  get()/
        get_name_value() call this, so callbacks that observe the metric
        (Speedometer, LogValidationMetricsCallback) are the loop's only
        sync points."""
        if state is None:
            state, self._device_state = self._device_state, None
            if state is None:
                return self
        import jax
        from . import profiler as _prof
        host = jax.device_get(state)
        _prof.record_host_sync("metric.sync")
        self._fold_synced(host)
        return self

    def _fold_synced(self, host_state):
        """Fold one already-read-back state into the host counters —
        bit-compatible with what get()/get_name_value() report."""
        s, n = host_state
        # the device accumulator is (f32, i32) — without jax x64 there
        # is no wider dtype to carry.  The f32 sum keeps integer counts
        # exact only to 2^24 and the i32 count wraps (negative) at
        # 2^31: a log interval that long has already lost precision
        # relative to the host counters, so say so instead of silently
        # diverging (sync more often — any callback reading the metric
        # does — or MXNET_DEVICE_METRICS=0).  A large count alone is
        # fine: i32 is exact all the way to the wrap.
        # analysis: allow(host-sync): s/n are host scalars — sync() already read them back (recorded as metric.sync) before folding here
        if (abs(float(s)) >= 2 ** 24 or int(n) < 0) \
                and not getattr(self, "_range_warned", False):
            self._range_warned = True
            logging.warning(
                "metric %r: device-resident accumulation exceeded the "
                "exact range of its (float32 sum, int32 count) state "
                "(sum=%s, count=%s); values may have lost precision vs "
                "the host path — sync at shorter intervals (any callback "
                "reading the metric) or set MXNET_DEVICE_METRICS=0",
                self.name, s, n)
        # analysis: allow(host-sync): same already-synced host scalars as above
        self.sum_metric += float(s)
        self.num_inst += int(n)

    def _device_state_or_init(self):
        """Pending device state if any, else a fresh zero state — the
        initial value a scan carry starts from, so K-step accumulation
        continues (not restarts) an in-progress interval."""
        return self._device_state if self._device_state is not None \
            else self.device_init()

    def _take_device_state(self):
        """:meth:`_device_state_or_init` with OWNERSHIP TRANSFER: the
        pending state is detached from the metric before it is handed
        to a donating scan dispatch (run_steps/step_k donate the carry
        — its buffers are deleted by XLA).  If the dispatch then fails
        at execution time, the metric holds None instead of pointing
        at donated-and-deleted buffers, so a later sync() degrades to
        a lost interval rather than a jax 'Array has been deleted'
        crash; on success _absorb_device_state installs the new
        carry."""
        state = self._device_state_or_init()
        self._device_state = None
        return state

    def _absorb_device_state(self, state):
        """Adopt a functional state (a finished scan carry) as this
        metric's pending accumulation.  The carry was seeded by
        _device_state_or_init, so it supersedes the old pending state."""
        self._device_state = state

    def _device_sig(self):
        """Hashable identity of the traced device-update math — joins
        jit/scan cache keys so two differently-configured metrics can
        never share a compiled program.

        Non-primitive hyperparameters (lists, arrays, callables) key by
        OBJECT IDENTITY: the signature cannot prove two of them equal,
        so such metrics simply never share a cache entry.  This is safe
        against id() reuse because every cache holding a _device_sig key
        (the global fold cache below, Module._run_steps_cache,
        Trainer._step_k_cache) stores a closure over the metric, pinning
        it — and through ``self._kwargs`` the keyed object — alive for
        the cache entry's lifetime."""
        kw = []
        for k, v in sorted(self._kwargs.items()):
            if isinstance(v, (int, float, str, bool, type(None))):
                kw.append((k, v))
            else:
                kw.append((k, f"id:{id(v)}"))
        cls = type(self)
        return (f"{cls.__module__}.{cls.__qualname__}",
                tuple(self.output_names or ()),
                tuple(self.label_names or ()), tuple(kw))

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self._device_state = None

    def get(self):
        self.sync()
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


register = _METRIC_REGISTRY.register


def create(metric, *args, **kwargs):
    """reference: metric.py create."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite_metric = CompositeEvalMetric()
        for child_metric in metric:
            composite_metric.add(create(child_metric, *args, **kwargs))
        return composite_metric
    return _METRIC_REGISTRY.get(metric)(*args, **kwargs)


@register
class CompositeEvalMetric(EvalMetric):
    """reference: metric.py CompositeEvalMetric."""

    def __init__(self, metrics=None, name='composite',
                 output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)
        if metrics is None:
            metrics = []
        self.metrics = [create(i) for i in metrics]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError(f"Metric index {index} is out of range 0 and "
                              f"{len(self.metrics)}")

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    # -- device path: capable iff EVERY child is (a scan carry must hold
    # the whole composite); state = tuple of child states -----------------
    @property
    def device_capable(self):
        return bool(self.metrics) and \
            all(m.device_capable for m in self.metrics)

    def device_init(self):
        return tuple(m.device_init() for m in self.metrics)

    def device_update(self, state, labels, preds):
        return tuple(m.device_update(st, labels, preds)
                     for m, st in zip(self.metrics, state))

    def device_update_dict(self, state, label, pred):
        return tuple(m.device_update_dict(st, label, pred)
                     for m, st in zip(self.metrics, state))

    def update_device(self, labels, preds):
        """ONE jitted fold per batch for the WHOLE composite — k child
        metrics never mean k dispatches on the training hot path (the
        same dispatch discipline as a plain metric's fused fold).
        Pending state still lives on the CHILDREN (sync gathers it from
        there in one device_get) — never on the composite itself."""
        labels = [self._as_device(x) for x in labels]
        preds = [self._as_device(x) for x in preds]
        state = self._device_state_or_init()
        self._absorb_device_state(
            self._device_update_jitted()(state, labels, preds))

    def accumulate(self, labels, preds):
        if self.device_enabled():
            self.update_device(labels, preds)
            return
        for metric in self.metrics:
            metric.accumulate(labels, preds)

    def accumulate_dict(self, label, pred):
        if self.device_enabled():
            # dict form: every child's output_names/label_names
            # selection happens at trace time inside the ONE program
            label = {k: self._as_device(v) for k, v in label.items()}
            pred = {k: self._as_device(v) for k, v in pred.items()}
            state = self._device_state_or_init()
            self._absorb_device_state(
                self._device_update_jitted(dict_form=True)(
                    state, label, pred))
            return
        for metric in self.metrics:
            metric.accumulate_dict(label, pred)

    def _device_state_or_init(self):
        return tuple(m._device_state_or_init() for m in self.metrics)

    def _take_device_state(self):
        return tuple(m._take_device_state() for m in self.metrics)

    def _absorb_device_state(self, state):
        for m, st in zip(self.metrics, state):
            m._absorb_device_state(st)

    def _device_sig(self):
        return (type(self).__name__,) + \
            tuple(m._device_sig() for m in self.metrics)

    def _device_kwargs_shareable(self):
        # the composite's own _kwargs is always empty — whether its
        # fused fold may live in the unbounded global cache is decided
        # by the CHILDREN: an id-keyed child signature must pin the jit
        # on the instance, or per-epoch composites would grow the
        # global cache (and pin themselves alive) without limit
        return all(m._device_kwargs_shareable() for m in self.metrics)

    def sync(self, state=None):
        """ONE readback for the whole composite: every child's pending
        state travels in a single device_get instead of one per child."""
        if state is not None:
            self._absorb_device_state(state)
        pend = [m for m in self.metrics if m._device_state is not None]
        if not pend:
            return self
        import jax
        from . import profiler as _prof
        host = jax.device_get([m._device_state for m in pend])
        _prof.record_host_sync("metric.sync")
        for m, h in zip(pend, host):
            m._device_state = None
            m._fold_synced(h)
        return self

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        self.sync()
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, numpy.generic)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


@register
class Accuracy(EvalMetric):
    """reference: metric.py Accuracy."""

    def __init__(self, axis=1, name='accuracy',
                 output_names=None, label_names=None):
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred_label = _np(pred_label)
            label = _np(label)
            if pred_label.shape != label.shape:
                pred_label = numpy.argmax(pred_label, axis=self.axis)
            pred_label = pred_label.astype('int32').flatten()
            label = label.astype('int32').flatten()
            check_label_shapes(label, pred_label, shape=1)
            self.sum_metric += (pred_label == label).sum()
            self.num_inst += len(pred_label)

    device_capable = True

    def device_update(self, state, labels, preds):
        import jax.numpy as jnp
        check_label_shapes(labels, preds)
        s, n = state
        for label, pred_label in zip(labels, preds):
            if pred_label.shape != label.shape:
                pred_label = jnp.argmax(pred_label, axis=self.axis)
            pred_label = pred_label.astype(jnp.int32).ravel()
            label = label.astype(jnp.int32).ravel()
            check_label_shapes(label, pred_label, shape=1)
            s = s + (pred_label == label).sum().astype(jnp.float32)
            n = n + pred_label.shape[0]
        return (s, n)


@register
class TopKAccuracy(EvalMetric):
    """reference: metric.py TopKAccuracy."""

    def __init__(self, top_k=1, name='top_k_accuracy',
                 output_names=None, label_names=None):
        super().__init__(name, top_k=top_k, output_names=output_names,
                         label_names=label_names)
        self.top_k = top_k
        assert self.top_k > 1, 'Please use Accuracy if top_k is no more than 1'
        self.name += '_%d' % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            assert len(pred.shape) <= 2, \
                'Predictions should be no more than 2 dims'
            pred = _np(pred).astype('float32')
            label = _np(label).astype('int32').ravel()
            check_label_shapes(label, pred)
            if pred.ndim == 1:
                self.sum_metric += int((pred.astype('int32') == label)
                                       .sum())
            else:
                k = min(pred.shape[1], self.top_k)
                # top-k SET membership via stable descending sort: on
                # ties at the k-th boundary the LOWER index wins —
                # the exact tie rule jax.lax.top_k documents, so the
                # host and device paths agree bit-for-bit even on tied
                # scores (argpartition's tie choice is unspecified).
                # NaN counts as MAXIMAL (lax.top_k's total order, and
                # what argpartition's sort-NaN-last did for the "k
                # largest"); plain argsort(-pred) would instead sort
                # NaN last and silently EXCLUDE it from the top k.
                # One documented gap: a row holding BOTH NaN and +inf
                # ties them here (NaN maps onto inf, lower index wins)
                # while lax.top_k ranks NaN strictly above +inf — the
                # two paths can pick different members of such a row
                key = numpy.where(numpy.isnan(pred), numpy.inf, pred)
                top = numpy.argsort(-key, axis=1, kind='stable')[:, :k]
                self.sum_metric += int(
                    (top == label[:, None]).any(axis=1).sum())
            self.num_inst += pred.shape[0]

    device_capable = True

    def device_update(self, state, labels, preds):
        import jax
        import jax.numpy as jnp
        check_label_shapes(labels, preds)
        s, n = state
        for label, pred in zip(labels, preds):
            assert len(pred.shape) <= 2, \
                'Predictions should be no more than 2 dims'
            label = label.astype(jnp.int32).ravel()
            if pred.ndim == 1:
                s = s + (pred.astype(jnp.int32) == label).sum() \
                    .astype(jnp.float32)
            else:
                k = min(pred.shape[1], self.top_k)
                # lax.top_k breaks ties in favor of the lower index —
                # the same rule the host path's stable descending sort
                # applies, so both paths pick the SAME member set even
                # on tied scores (bit-identical counts)
                _, top = jax.lax.top_k(pred.astype(jnp.float32), k)
                s = s + (top == label[:, None]).any(axis=1).sum() \
                    .astype(jnp.float32)
            n = n + pred.shape[0]
        return (s, n)


class _DeferredBadLabels:
    """Mixin for device paths whose label validation cannot run
    mid-trace: the state grows a third slot counting out-of-range
    labels — ``(sum_metric f32, num_inst i32, bad i32)`` — and the
    error the host path raises per batch surfaces at the interval's
    sync point instead (get/callback), STICKY until reset() so a
    caught first error can't turn into silently-clean later reads.
    Subclass ``device_update`` must exclude a bad batch's score/count
    contributions entirely (the host path raises BEFORE accumulating
    the batch, so counters match it up to and including the bad
    batch).  Known asymmetry of deferral: good batches folded AFTER a
    bad one still count here, while the host loop died at the bad
    batch and never saw them — a caller that catches the error and
    keeps reading counters can observe the difference."""

    _bad_exc = ValueError
    _bad_msg = "out-of-range labels in device-accumulated metric"

    def device_init(self):
        import jax.numpy as jnp
        return (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32),
                jnp.zeros((), jnp.int32))

    def _fold_synced(self, host_state):
        # fold the good batches FIRST (the host path keeps previously
        # accumulated batches when a bad one raises), then flag — the
        # raise itself happens in sync() below
        s, n, bad = host_state
        if int(bad):
            self._bad_label_seen = True
        super()._fold_synced((s, n))

    def sync(self, state=None):
        out = super().sync(state)
        if getattr(self, "_bad_label_seen", False):
            raise self._bad_exc(self._bad_msg)
        return out

    def reset(self):
        super().reset()
        self._bad_label_seen = False


@register
class F1(_DeferredBadLabels, EvalMetric):
    """Binary-classification F1 (reference: metric.py F1)."""

    _bad_msg = "F1 currently only supports binary classification."

    def __init__(self, name='f1', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _np(pred)
            # ravel BEFORE the vectorized compares: an (n,1) label would
            # broadcast against the (n,) argmax into an (n,n) matrix
            label = _np(label).astype('int32').ravel()
            pred_label = numpy.argmax(pred, axis=1)
            check_label_shapes(label, pred)
            if label.size and (label.min() < 0 or label.max() > 1):
                raise ValueError(
                    "F1 currently only supports binary classification.")
            # ONE pass over the confusion cells: 2*pred+label indexes
            # them (3=tp, 2=fp, 1=fn, 0=tn) — a single bincount replaces
            # three separate masked-sum reductions.  2*tp/(2*tp+fp+fn) is
            # the precision/recall harmonic mean, 0/0 -> 0 convention.
            c = numpy.bincount(pred_label * 2 + label, minlength=4)
            tp, fp, fn = float(c[3]), float(c[2]), float(c[1])
            denom = 2 * tp + fp + fn
            self.sum_metric += (2 * tp / denom) if denom > 0 else 0.
            self.num_inst += 1

    device_capable = True

    def device_update(self, state, labels, preds):
        import jax.numpy as jnp
        check_label_shapes(labels, preds)
        s, n, bad = state
        for label, pred in zip(labels, preds):
            label = label.astype(jnp.int32).ravel()
            nbad = ((label < 0) | (label > 1)).sum().astype(jnp.int32)
            bad = bad + nbad
            # a batch with ANY out-of-range label contributes NOTHING —
            # the host path raises before accumulating it, so excluding
            # it keeps sum_metric/num_inst identical after the deferred
            # error fires at sync (labels are clipped only so the
            # bincount below stays well-defined for the excluded batch)
            ok = (nbad == 0).astype(jnp.float32)
            pred_label = jnp.argmax(pred, axis=1).astype(jnp.int32)
            # same one-pass confusion bincount as the host path, as one
            # fused reduction in the jit
            c = jnp.bincount(pred_label * 2 + jnp.clip(label, 0, 1),
                             length=4)
            tp = c[3].astype(jnp.float32)
            fp = c[2].astype(jnp.float32)
            fn = c[1].astype(jnp.float32)
            denom = 2 * tp + fp + fn
            s = s + ok * jnp.where(denom > 0,
                                   2 * tp / jnp.maximum(denom, 1.0), 0.0)
            n = n + ok.astype(jnp.int32)
        return (s, n, bad)


@register
class Perplexity(_DeferredBadLabels, EvalMetric):
    """reference: metric.py Perplexity."""

    def __init__(self, ignore_label, axis=-1, name='perplexity',
                 output_names=None, label_names=None):
        super().__init__(name, ignore_label=ignore_label, axis=axis,
                         output_names=output_names, label_names=label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.
        num = 0
        for label, pred in zip(labels, preds):
            label = _np(label)
            pred = _np(pred)
            assert label.size == pred.size / pred.shape[-1], \
                f"shape mismatch: {label.shape} vs. {pred.shape}"
            label = label.reshape((label.size,)).astype('int32')
            probs = numpy.take_along_axis(
                pred.reshape(-1, pred.shape[-1]), label[:, None],
                axis=-1).squeeze(-1)
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(probs.dtype)
                num -= int(ignore.sum())
                probs = probs * (1 - ignore) + ignore
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += probs.size
        # accumulate total loss/count; get() exponentiates the GLOBAL mean
        # (reference: metric.py Perplexity.get)
        self.sum_metric += loss
        self.num_inst += num

    device_capable = True
    _bad_msg = ("label index out of range for the class axis "
                "(detected at metric sync; the host path raises "
                "IndexError per batch)")
    _bad_exc = IndexError

    def device_update(self, state, labels, preds):
        import jax.numpy as jnp
        assert len(labels) == len(preds)
        s, n, bad = state
        for label, pred in zip(labels, preds):
            label = label.reshape((-1,)).astype(jnp.int32)
            nclass = pred.shape[-1]
            # same deferred range check as CrossEntropy: numpy's
            # take_along_axis raises outside [-nclass, nclass) and
            # wraps in-range negatives; bad batches contribute nothing
            nbad = ((label < -nclass) | (label >= nclass)).sum() \
                .astype(jnp.int32)
            bad = bad + nbad
            ok = (nbad == 0)
            oki = ok.astype(jnp.int32)
            probs = jnp.take_along_axis(
                pred.reshape(-1, nclass), (label % nclass)[:, None],
                axis=-1).squeeze(-1)
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(probs.dtype)
                n = n - oki * ignore.sum().astype(jnp.int32)
                probs = probs * (1 - ignore) + ignore
            s = s - ok.astype(jnp.float32) * \
                jnp.sum(jnp.log(jnp.maximum(1e-10, probs))) \
                .astype(jnp.float32)
            n = n + oki * probs.shape[0]
        return (s, n, bad)

    def get(self):
        self.sync()
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, float(numpy.exp(self.sum_metric / self.num_inst)))


class _RegressionMetric(EvalMetric):
    """Shared per-batch regression scoring: subclasses define the batch
    score over the residual; the mean-of-batch-scores accumulation (one
    num_inst per batch) is the reference contract for all three."""

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _np(label)
            pred = _np(pred)
            if label.ndim == 1:
                label = label[:, None]
            if pred.ndim == 1:
                pred = pred[:, None]
            self.sum_metric += self._score(label - pred)
            self.num_inst += 1

    device_capable = True

    def device_update(self, state, labels, preds):
        import jax.numpy as jnp
        check_label_shapes(labels, preds)
        s, n = state
        for label, pred in zip(labels, preds):
            if label.ndim == 1:
                label = label[:, None]
            if pred.ndim == 1:
                pred = pred[:, None]
            s = s + self._device_score(label - pred).astype(jnp.float32)
            n = n + 1
        return (s, n)


@register
class MAE(_RegressionMetric):
    """reference: metric.py MAE."""

    def __init__(self, name='mae', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    @staticmethod
    def _score(err):
        return numpy.abs(err).mean()

    @staticmethod
    def _device_score(err):
        import jax.numpy as jnp
        return jnp.abs(err).mean()


@register
class MSE(_RegressionMetric):
    """reference: metric.py MSE."""

    def __init__(self, name='mse', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    @staticmethod
    def _score(err):
        return (err ** 2.0).mean()

    @staticmethod
    def _device_score(err):
        return (err ** 2.0).mean()


@register
class RMSE(_RegressionMetric):
    """reference: metric.py RMSE."""

    def __init__(self, name='rmse', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    @staticmethod
    def _score(err):
        return numpy.sqrt((err ** 2.0).mean())

    @staticmethod
    def _device_score(err):
        import jax.numpy as jnp
        return jnp.sqrt((err ** 2.0).mean())


@register
class CrossEntropy(_DeferredBadLabels, EvalMetric):
    """reference: metric.py CrossEntropy."""

    def __init__(self, eps=1e-12, name='cross-entropy',
                 output_names=None, label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _np(label)
            pred = _np(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]

    device_capable = True
    _bad_msg = ("label index out of range for the class axis "
                "(detected at metric sync; the host path raises "
                "IndexError per batch)")
    _bad_exc = IndexError

    def device_update(self, state, labels, preds):
        import jax.numpy as jnp
        check_label_shapes(labels, preds)
        s, n, bad = state
        for label, pred in zip(labels, preds):
            label = label.ravel().astype(jnp.int32)
            assert label.shape[0] == pred.shape[0]
            nclass = pred.shape[-1]
            # host-path parity on malformed labels: numpy's gather
            # raises on indices outside [-nclass, nclass) and WRAPS
            # in-range negatives; jax would silently clamp, so count
            # the out-of-range ones (deferred raise at sync, batch
            # excluded) and gather modulo nclass (= numpy's wrap)
            nbad = ((label < -nclass) | (label >= nclass)).sum() \
                .astype(jnp.int32)
            bad = bad + nbad
            ok = (nbad == 0)
            prob = pred[jnp.arange(label.shape[0]), label % nclass]
            s = s + ok.astype(jnp.float32) * \
                (-jnp.log(prob + self.eps)).sum().astype(jnp.float32)
            n = n + jnp.where(ok, label.shape[0], 0).astype(jnp.int32)
        return (s, n, bad)


@register
class NegativeLogLikelihood(CrossEntropy):
    """reference: metric.py NegativeLogLikelihood — same per-example
    -log p[label] accumulation as CrossEntropy, under its NLL name."""

    def __init__(self, eps=1e-12, name='nll-loss',
                 output_names=None, label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


@register
class PearsonCorrelation(EvalMetric):
    """reference: metric.py PearsonCorrelation."""

    def __init__(self, name='pearsonr', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            check_label_shapes(label, pred, 1)
            label = _np(label).ravel()
            pred = _np(pred).ravel()
            self.sum_metric += numpy.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of a loss-valued output (reference: metric.py Loss)."""

    def __init__(self, name='loss', output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, _, preds):
        for pred in preds:
            self.sum_metric += float(_np(pred).sum())
            self.num_inst += _np(pred).size if not numpy.isscalar(pred) else 1

    device_capable = True

    def device_update(self, state, _, preds):
        import jax.numpy as jnp
        s, n = state
        for pred in preds:
            s = s + pred.sum().astype(jnp.float32)
            n = n + pred.size
        return (s, n)


@register
class Torch(Loss):
    """reference: metric.py Torch (alias of Loss with torch name)."""

    def __init__(self, name='torch', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name='caffe', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wrap feval(label, pred) (reference: metric.py CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find('<') != -1:
                name = 'custom(%s)' % name
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = _np(label)
            pred = _np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


_METRIC_REGISTRY.alias('acc', 'accuracy')
_METRIC_REGISTRY.alias('top_k_acc', 'topkaccuracy')
_METRIC_REGISTRY.alias('top_k_accuracy', 'topkaccuracy')
_METRIC_REGISTRY.alias('ce', 'crossentropy')
_METRIC_REGISTRY.alias('cross-entropy', 'crossentropy')
_METRIC_REGISTRY.alias('nll_loss', 'negativeloglikelihood')
_METRIC_REGISTRY.alias('pearsonr', 'pearsoncorrelation')


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """reference: metric.py np — wrap a numpy feval as a metric factory."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
