"""Dapper-style span tracing for the whole cluster (docs/OBSERVABILITY.md).

Every observability signal the repo had grown — profiler counters, wire
clocks, serving latency rings — was process-local; nothing could show a
push travel worker→server→ack or put a failover's rebuild window on a
timeline.  This module is the cross-process half (the span model of
Dapper, the production shape of TensorFlow's cross-process timelines,
arXiv:1605.08695; MXNet's engine-integrated profiler, arXiv:1512.01274):

* **Spans** — ``span_begin``/``span_end`` (or ``with span(...):``) with a
  thread-local current-span stack, so nested calls build a parent/child
  tree with zero caller plumbing.  Durations come from the MONOTONIC
  clock; wall-clock placement maps through a per-process anchor taken at
  import (``time.time_ns() - time.monotonic_ns()``), so a span's
  duration can never be warped by an NTP step mid-span.
* **Wire propagation** — ``current_ctx()`` is the (trace_id, span_id)
  pair the kvstore client stamps onto request envelopes
  (``kvstore._ServerConn``); the server opens a child span around its
  handling (``kvstore_server._serve_conn``), so one trace spans
  processes.  Replays re-send the ORIGINAL envelope, trace field
  included — a reconnect annotates the same trace instead of starting a
  new one.
* **Flush** — spans land in a bounded in-memory ring and, when
  ``MXNET_TRACE_DIR`` is set, append to
  ``<dir>/<role>-<rank>.trace.jsonl``: append-only, fsync'd every
  ``MXNET_TRACE_FLUSH_N`` spans (and at exit), torn-line tolerant on
  read exactly like the autotune journal — a SIGKILLed server loses at
  most the unflushed tail, never the file.  ``tools/trace_merge.py
  --spans`` stitches the per-process files into one chrome://tracing
  timeline with cross-process flow arrows.

Master switch: ``MXNET_TRACE=1``.  Off (the default) every entry point
returns before touching a lock or allocating — call sites guard with
``tracing.enabled()`` or use ``span()``'s shared null context — and the
kvstore envelope stays byte-identical to the untraced wire (pinned by
tests/test_tracing.py via ``profiler.channel_bytes``).
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Optional

from .base import env

# wall-clock anchor for the monotonic span clock: epoch_us(span) =
# (monotonic_ns + anchor) / 1e3.  Taken ONCE at import so every span in
# this process shares one mapping; cross-process residual skew is
# estimated at merge time from envelope send/recv pairs.
_ANCHOR_NS = time.time_ns() - time.monotonic_ns()

_NULL = __import__("contextlib").nullcontext()

_lock = threading.Lock()
_tls = threading.local()


class _State:
    """Module config + ring, re-readable for tests (``reconfigure``)."""

    def __init__(self):
        self.on = False
        self.dir = ""
        self.ring = deque(maxlen=4096)
        self.flush_n = 32
        # cached at reconfigure(): role/rank and the journal path are
        # process-constant — re-deriving them from os.environ per span
        # would tax the hot path for nothing
        self.role = "local"
        self.rank = "0"
        self.path = None
        self.recorded = 0
        self._fh = None
        self._unflushed = 0
        # set when the journal dir proved unwritable: stop retrying the
        # open() on every span (reconfigure() re-arms)
        self._file_dead = False


_state = _State()


def reconfigure():
    """(Re-)read the MXNET_TRACE* env knobs — import calls this once;
    tests call it again after monkeypatching the env.  Closes any open
    trace file so the next span reopens under the new settings."""
    with _lock:
        _close_file_locked()
        _state._file_dead = False
        _state.on = bool(env("MXNET_TRACE", False))
        _state.dir = str(env("MXNET_TRACE_DIR", "") or "")
        _state.flush_n = max(1, int(env("MXNET_TRACE_FLUSH_N", 32)))
        _state.role, _state.rank = role_rank()
        _state.path = os.path.join(
            _state.dir, "%s-%s.trace.jsonl" % (_state.role, _state.rank)
        ) if _state.dir else None
        ring = max(16, int(env("MXNET_TRACE_RING", 4096)))
        if ring != _state.ring.maxlen:
            _state.ring = deque(_state.ring, maxlen=ring)


def enabled() -> bool:
    """The master switch (``MXNET_TRACE=1``) — THE guard every
    instrumentation site checks first, so a disabled trace costs one
    attribute read."""
    return _state.on


def role_rank():
    """This process's (role, rank) from the launcher's DMLC env —
    ``("local", "0")`` outside a launcher job.  THE one derivation,
    shared by span records, ``profiler.snapshot()`` and
    ``distributed.cluster_stats()`` so the three can never disagree on
    how a process is labeled."""
    role = os.environ.get("DMLC_ROLE") or "local"
    rank = os.environ.get("DMLC_SERVER_ID" if role == "server"
                          else "DMLC_WORKER_ID") or "0"
    return role, rank


def trace_file_path() -> Optional[str]:
    """Where this process flushes spans (None when MXNET_TRACE_DIR is
    unset): ``<dir>/<role>-<rank>.trace.jsonl`` — unique per process in
    a launcher job, so the merge tool gets one timeline track each.
    Cached at :func:`reconfigure`, like everything derived from the
    process-constant env."""
    return _state.path


def new_id() -> str:
    return uuid.uuid4().hex[:16]


def now_us() -> float:
    """Epoch microseconds on the anchored monotonic clock (what span
    ``ts`` fields and the envelope send stamp use)."""
    return (time.monotonic_ns() + _ANCHOR_NS) / 1e3


class Span:
    """One in-flight span.  ``args`` may be mutated until span_end."""

    __slots__ = ("name", "cat", "trace", "span", "parent", "t0", "args",
                 "detached")

    def __init__(self, name, cat, trace, span_id, parent, args, detached):
        self.name = name
        self.cat = cat
        self.trace = trace
        self.span = span_id
        self.parent = parent
        self.t0 = time.monotonic_ns()
        self.args = args
        self.detached = detached

    def ctx(self):
        return (self.trace, self.span)


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Optional[Span]:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def current_ctx() -> Optional[tuple]:
    """(trace_id, span_id) of the thread's innermost open span, or None
    — the value the kvstore client stamps onto request envelopes."""
    sp = current_span()
    return None if sp is None else (sp.trace, sp.span)


def span_begin(name, cat="span", ctx=None, detach=False, args=None
               ) -> Optional[Span]:
    """Open a span.  ``ctx=(trace_id, parent_span_id)`` adopts a remote
    parent (the server side of a traced envelope); otherwise the
    thread's current span is the parent, and with neither this span
    roots a fresh trace.  ``detach=True`` keeps it OFF the thread-local
    stack — for spans that end on another thread (a batcher reply slot).
    Returns None (and does nothing) when tracing is off."""
    if not _state.on:
        return None
    if ctx is not None:
        trace, parent = str(ctx[0]), (str(ctx[1]) if ctx[1] else None)
    else:
        cur = current_span()
        if cur is not None:
            trace, parent = cur.trace, cur.span
        else:
            trace, parent = new_id(), None
    sp = Span(str(name), cat, trace, new_id(), parent, args, detach)
    if not detach:
        _stack().append(sp)
    return sp


def span_end(sp: Optional[Span], args=None) -> None:
    """Close a span opened by :func:`span_begin` (None is a no-op, so
    callers never re-check the master switch)."""
    if sp is None:
        return
    t1 = time.monotonic_ns()
    if not sp.detached:
        st = getattr(_tls, "stack", None)
        if st and sp in st:
            # normally the top; a crossed end (rare) removes in place
            st.remove(sp)
    if args:
        sp.args = dict(sp.args or {}, **args)
    _record(sp.name, sp.cat, sp.trace, sp.span, sp.parent,
            sp.t0, t1, sp.args)


class _SpanCtx:
    __slots__ = ("_sp", "_a")

    def __init__(self, name, cat, ctx, args):
        self._a = (name, cat, ctx, args)
        self._sp = None

    def __enter__(self):
        name, cat, ctx, args = self._a
        self._sp = span_begin(name, cat=cat, ctx=ctx, args=args)
        return self._sp

    def __exit__(self, *exc):
        span_end(self._sp)


def span(name, cat="span", ctx=None, args=None):
    """``with tracing.span("kv.pull"):`` — the one-liner form.  Returns
    a shared null context when tracing is off."""
    if not _state.on:
        return _NULL
    return _SpanCtx(name, cat, ctx, args)


def instant(name, cat="instant", args=None) -> None:
    """A zero-duration marker under the current span (dedup hits,
    roster bumps — things with a moment but no extent)."""
    if not _state.on:
        return
    cur = current_span()
    trace = cur.trace if cur is not None else new_id()
    parent = cur.span if cur is not None else None
    t = time.monotonic_ns()
    _record(str(name), cat, trace, new_id(), parent, t, t, args)


def add_span(name, t0_mono_ns, t1_mono_ns, cat="span", ctx=None,
             args=None) -> None:
    """Record an already-timed span (both ends on the monotonic clock)
    — for intervals that cross threads, like a pull handle's
    enqueue→resolved wire round."""
    if not _state.on:
        return
    if ctx is not None:
        trace, parent = str(ctx[0]), (str(ctx[1]) if ctx[1] else None)
    else:
        cur = current_span()
        trace = cur.trace if cur is not None else new_id()
        parent = cur.span if cur is not None else None
    _record(str(name), cat, trace, new_id(), parent,
            int(t0_mono_ns), int(t1_mono_ns), args)


def _record(name, cat, trace, span_id, parent, t0_ns, t1_ns, args):
    rec = {
        "name": name, "cat": cat,
        "trace": trace, "span": span_id, "parent": parent,
        "ts": round((t0_ns + _ANCHOR_NS) / 1e3, 3),
        "dur": round(max(0, t1_ns - t0_ns) / 1e3, 3),
        "pid": os.getpid(),
        "tid": threading.get_ident() % 100000,
        "role": _state.role, "rank": _state.rank,
    }
    if args:
        rec["args"] = args
    # json-encode OUTSIDE the lock: the lock should cover only the ring
    # append and the (ordered) file write, not per-record CPU work.
    # The periodic flush+fsync does stay under the lock — it is what
    # bounds a SIGKILL's span loss to MXNET_TRACE_FLUSH_N, runs once
    # per flush_n records, and keeping it ordered beats a second
    # writer thread for an opt-in debugging feature.
    line = None
    if _state.path is not None and not _state._file_dead:
        line = json.dumps(rec, sort_keys=True)
    with _lock:
        _state.ring.append(rec)
        _state.recorded += 1
        if line is not None:
            _write_locked(line)


def _write_locked(line):
    path = _state.path
    if path is None or _state._file_dead:
        return
    try:
        if _state._fh is None:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            _state._fh = open(path, "a")
        _state._fh.write(line + "\n")
        _state._unflushed += 1
        if _state._unflushed >= _state.flush_n:
            _flush_locked()
    except OSError:
        # tracing must never take the job down: close the journal, mark
        # it dead (no per-span open() retries against an unwritable
        # dir) and keep the ring — the stats op still serves counters
        _close_file_locked()
        _state._file_dead = True
        _state._unflushed = 0


def _flush_locked():
    if _state._fh is None:
        return
    try:
        _state._fh.flush()
        os.fsync(_state._fh.fileno())
    except OSError:
        pass
    _state._unflushed = 0


def _close_file_locked():
    _flush_locked()
    if _state._fh is not None:
        try:
            _state._fh.close()
        except OSError:
            pass
        _state._fh = None


def flush() -> None:
    """Force the file buffer to disk (span_end fsyncs every
    MXNET_TRACE_FLUSH_N spans on its own; atexit calls this too)."""
    with _lock:
        _flush_locked()


def ring_records() -> list:
    """The bounded in-memory ring, oldest first (the stats op's and the
    in-process tests' view — no file round trip needed)."""
    with _lock:
        return list(_state.ring)


def stats() -> dict:
    """The tracing block of ``profiler.snapshot()``."""
    with _lock:
        return {
            "enabled": _state.on,
            "recorded": _state.recorded,
            "ring": len(_state.ring),
            "ring_max": _state.ring.maxlen,
            "file": trace_file_path(),
        }


def reset() -> None:
    """Clear the ring and counters (tests); the file, being append-only
    evidence, is left alone."""
    with _lock:
        _state.ring.clear()
        _state.recorded = 0


def read_trace_file(path) -> list:
    """Parse one ``*.trace.jsonl`` — TORN-LINE TOLERANT: a process
    SIGKILLed mid-append leaves at most one undecodable line, which is
    skipped (the autotune journal's resume contract applied to traces).
    Returns the span records in file order."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail from a SIGKILL mid-write
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


reconfigure()
atexit.register(flush)
