"""Executor: jit-compiled forward/backward over a Symbol graph.

TPU-native equivalent of the reference's GraphExecutor
(src/executor/graph_executor.cc:507 Init → memory planning → cached engine
ops) and the Python wrapper (python/mxnet/executor.py).  The entire
reference pipeline — gradient-graph construction (InitFullGraph :253),
memory planning (PlanMemory :868), op bulking (InitOpSegs :1302) — is
replaced by ONE idea: the symbol graph is interpreted as a pure jax function
and jit-compiled; XLA performs buffer assignment, fusion and scheduling.

The fused forward+backward program is differentiated with ``jax.vjp`` (the
XLA-native Gradient pass).  ``forward`` is *lazy*: outputs materialize on
first read, and a training step that calls forward→backward executes as a
single XLA program — the analog (and superset) of the reference's bulked
segment execution.
"""
from __future__ import annotations

import functools
from contextlib import contextmanager, nullcontext as _nullcontext
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError, env as _base_env
from .context import Context, current_context
from . import random as _rnd
from .ndarray import NDArray
from .ndarray.ndarray import zeros as nd_zeros
from .ops import registry as _reg
from .symbol.symbol import Symbol, node_num_outputs, _topo_sort


# Ops kept in float32 under mixed precision: normalization statistics and
# loss heads.  This is the TPU-native analog of the reference's fp16
# training recipe (example train scripts cast data to fp16 but cuDNN
# BatchNorm keeps fp32 statistics, and SoftmaxOutput runs on an fp32 cast).
AMP_FP32_OPS = frozenset({
    "InstanceNorm", "L2Normalization", "LRN", "norm",
    "SoftmaxOutput", "SoftmaxActivation", "softmax", "log_softmax",
    "log_softmax_mx", "LinearRegressionOutput", "LogisticRegressionOutput",
    "MAERegressionOutput", "MakeLoss", "SVMOutput", "CTCLoss",
    "softmax_cross_entropy",
})

# Ops with a SPLIT precision contract: the listed input indices are cast to
# the compute dtype (the big activation tensors), everything else keeps its
# master precision (small per-channel params / statistics).  BatchNorm
# accumulates its stats in fp32 internally (ops/nn.py _batch_norm), so the
# (N,C,H,W) activation never round-trips HBM in fp32 — the TPU equivalent of
# the reference's fused cuDNN BN (cudnn_batch_norm-inl.h keeps fp32 stats
# over an fp16 data path).
AMP_SPLIT_OPS = {"BatchNorm": (0,)}


def maybe_mirror(run):
    """Wrap an interpreter in jax.checkpoint when
    MXNET_BACKWARD_DO_MIRROR is set (reference: graph_executor.cc:281
    mirror-recompute): activations are rematerialized in backward, trading
    FLOPs for HBM.  Returns a function with the same
    (args, aux, key, is_train) signature; remat always traces train mode
    (the only mode with a backward).

    MXNET_REMAT_POLICY selects what backward may keep:
      * "full" (default) — keep nothing: recompute the whole forward
        (~33% extra FLOPs, maximum memory relief).
      * "save_matmuls" — keep conv/FC outputs (tagged with
        checkpoint_name in ops/nn.py) and recompute only the cheap
        elementwise/normalization chains between them: most of the
        memory relief for a few percent of FLOPs — the right trade for
        batch-512 ResNet on a 16 GB chip.
    """
    from .base import env as _env
    if not _env("MXNET_BACKWARD_DO_MIRROR", False):
        return run
    policy_name = _env("MXNET_REMAT_POLICY", "full")
    kw = {}
    if policy_name == "save_matmuls":
        kw["policy"] = jax.checkpoint_policies.save_only_these_names(
            "conv_out", "matmul_out")
    elif policy_name != "full":
        raise MXNetError(
            f"MXNET_REMAT_POLICY={policy_name!r}: expected 'full' or "
            f"'save_matmuls'")
    remat = jax.checkpoint(lambda av, aux, k: run(av, aux, k, True), **kw)
    return lambda av, aux, k, _t: remat(av, aux, k)


def build_interpreter(sym: Symbol, compute_dtype=None):
    """Build ``run(arg_vals, aux_vals, key, is_train) -> (outs, new_aux)``.

    The returned function is pure — jit/vjp/vmap-compatible.  RNG ops get
    per-node subkeys split from ``key`` (replacement for the reference's
    per-device PRNG resource, src/resource.cc kRandom).

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) enables mixed precision: all
    floating-point op inputs are cast to it except ops in ``AMP_FP32_OPS``,
    which run in float32.  Master parameters stay float32 in HBM; the casts
    are inserted per-use and fused by XLA into the surrounding ops, so the
    MXU sees bf16 operands while optimizer state and normalization
    statistics keep full precision.
    """
    nodes = _topo_sort(sym.heads)
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    arg_pos = {n: i for i, n in enumerate(arg_names)}
    aux_pos = {n: i for i, n in enumerate(aux_names)}
    heads = sym.heads
    rng_ids = [id(n) for n in nodes
               if not n.is_variable and _reg.get(n.op).needs_rng]
    rng_index = {nid: i for i, nid in enumerate(rng_ids)}
    cd = jnp.dtype(compute_dtype) if compute_dtype is not None else None

    def _amp_cast(ins, op):
        split = AMP_SPLIT_OPS.get(op)
        if split is not None:
            return [v.astype(cd)
                    if (i in split and hasattr(v, "dtype")
                        and jnp.issubdtype(v.dtype, jnp.floating)
                        and v.dtype != cd) else v
                    for i, v in enumerate(ins)]
        want = jnp.float32 if op in AMP_FP32_OPS else cd
        return [v.astype(want)
                if (hasattr(v, "dtype")
                    and jnp.issubdtype(v.dtype, jnp.floating)
                    and v.dtype != want) else v
                for v in ins]

    def run(arg_vals, aux_vals, key, is_train, _collect=None):
        env = {}
        new_aux = list(aux_vals)
        if rng_ids:
            keys = jax.random.split(key, len(rng_ids))
        for n in nodes:
            if n.is_variable:
                if n.name in arg_pos:
                    env[(id(n), 0)] = arg_vals[arg_pos[n.name]]
                else:
                    env[(id(n), 0)] = aux_vals[aux_pos[n.name]]
                continue
            opdef = _reg.get(n.op)
            _reg.record_execution(n.op)
            ins = [env[(id(src), i)] for src, i in n.inputs]
            if cd is not None:
                ins = _amp_cast(ins, n.op)
            kwargs = dict(n.attrs)
            kwargs.pop("name", None)
            if opdef.takes_is_train:
                kwargs["is_train"] = is_train
            if opdef.needs_rng:
                outs = opdef.fn(keys[rng_index[id(n)]], *ins, **kwargs)
            else:
                outs = opdef.fn(*ins, **kwargs)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            if opdef.num_aux and opdef.takes_is_train and is_train:
                updates = outs[-opdef.num_aux:]
                outs = outs[:-opdef.num_aux]
                aux_inputs = n.inputs[-opdef.num_aux:]
                for (src, _), u in zip(aux_inputs, updates):
                    if src.is_variable and src.name in aux_pos:
                        new_aux[aux_pos[src.name]] = u
            for i, o in enumerate(outs):
                env[(id(n), i)] = o
            if _collect is not None:
                _collect(n, outs[:node_num_outputs(n)])
        out_vals = tuple(env[(id(h), i)] for h, i in heads)
        return out_vals, tuple(new_aux)

    # whether the program actually consumes the PRNG key: dispatch uses
    # this to skip the per-step eager fold_in (a device op — through a
    # remote-attached chip that is a per-step round-trip for nothing)
    run.needs_rng = bool(rng_ids)
    return run, arg_names, aux_names


def build_multi_step(step_body, donate=True):
    """Compile a single fused training step into a K-step ``lax.scan``
    program — the multi-step driver shared by ``Module.run_steps`` and
    ``gluon.Trainer.step_k`` (whole-program TPU execution à la Fischer &
    Saba, arXiv:1810.09868: the host leaves the training loop entirely,
    amortizing the per-dispatch host cost over K steps).

    ``step_body(carry, x, const) -> (carry, y)`` is the pure single-step
    function: ``carry`` holds everything that flows step-to-step (params,
    aux/BN statistics, optimizer state — and, for callers that fold a
    device-resident metric, the metric's ``(sum, count)`` state, so K
    steps of metric accumulation ride the same one dispatch with zero
    readbacks; see metric.EvalMetric.device_update), ``x`` holds the
    per-step inputs scanned over their leading K axis (data, labels,
    per-step lr/wd/t, RNG keys), and ``const`` holds step-invariant
    inputs (fixed params, state inputs).  Returns a jitted ``fn(carry, xs, const) -> (carry,
    ys)``; K is the leading dim of ``xs``, so the jit cache is keyed by
    (K, shapes, carry structure) for free.  With ``donate`` the carry
    buffers (params/aux/optimizer state) are donated — XLA updates them
    in place in HBM across all K steps, exactly like the single fused
    step does for one.
    """
    def k_steps(carry, xs, const):
        def body(c, x):
            return step_body(c, x, const)
        return jax.lax.scan(body, carry, xs)

    return jax.jit(k_steps, donate_argnums=(0,) if donate else ())


def fused_dist_knobs(k):
    """``(chunk_size, staleness)`` for the fused-dist drivers — one
    reader for the knob pair so Module and Trainer can never parse the
    envs differently.  Note a ``k`` that is not a multiple of the chunk
    produces one tail chunk with its own leading dimension, which
    compiles as its own XLA program (the jit cache keys on shape):
    size K-step calls as multiples of MXNET_KVSTORE_FUSED_CHUNK to pay
    exactly one compile."""
    from .base import env
    chunk = max(1, min(k, int(env("MXNET_KVSTORE_FUSED_CHUNK", 8))))
    staleness = max(0, int(env("MXNET_KVSTORE_FUSED_STALENESS", 1)))
    return chunk, staleness


def drive_chunked_dist(num_steps, chunk_size, staleness, dispatch_chunk,
                       ship_chunk):
    """The chunked-scan dist_async driver: overlap the kvstore wire
    behind the scanned compute (the MXNet dependency-engine thesis —
    overlap communication with computation, arXiv:1512.01274 — rebuilt
    on XLA async dispatch; PipeDream-shaped pipelining, arXiv:1806.03377).

    ``num_steps`` splits into ceil(num_steps/chunk_size) chunks.  Per
    chunk ``j``:

    1. if chunk ``j-1-staleness`` has a wire round in flight, BLOCK on
       it and hand its pulled weights to ``dispatch_chunk`` for
       adoption — with staleness 0 this is a barrier'd chunk boundary
       (the wire fully exposed, every chunk starts from the server's
       post-previous-chunk weights); with staleness S>=1 the round has
       had S chunks of compute to resolve, so the block is only the
       un-overlapped residue (profiler.record_wire_wait counts it),
    2. ``dispatch_chunk(j, lo, hi, adopted) -> grads_host`` dispatches
       the scanned compute for steps [lo, hi) and reads the chunk's
       per-step gradients back (blocking on the chunk's COMPUTE, never
       on the wire),
    3. ``ship_chunk(j, grads_host) -> handle`` pushes the gradients
       (fire-and-forget through the pipelined window) and enqueues the
       next pull; ``handle.wait() -> {name: host array}`` resolves it.

    The lag is EXACT, not just bounded: chunk ``j`` always adopts the
    round issued after chunk ``j-1-staleness``'s pushes, even when a
    fresher round happens to have resolved — determinism is what makes
    the staleness-1 analytic golden (and any future autotuned setting)
    simulable and therefore testable (tests/test_fused_dist.py).

    Fault composition: ``handle.wait()`` owns its own recovery — under
    MXNET_KVSTORE_ELASTIC an in-flight round whose server died mid-pull
    repairs the roster and REPLANS its unserved stripes from inside the
    wait (kvstore._PullHandle._replan), so this driver needs no
    elastic-specific control flow and elastic jobs run chunked instead
    of falling back to the eager per-step loop.

    Returns the FINAL round's pulled values — the server-authoritative
    weights at the sync point — or None when num_steps == 0."""
    import math
    from . import tracing as _tr
    from . import health as _health
    n_chunks = math.ceil(num_steps / chunk_size)
    pending = {}
    for j in range(n_chunks):
        # liveness breadcrumb per chunk: the health snapshot's
        # progress_age_s separates a stalled driver from a slow one
        _health.note_progress("fused.chunk")
        # one span per chunk: its children separate the scanned COMPUTE
        # from the exposed wire (the _PullHandle's kv.wire_wait span
        # lands under fused.adopt_wait, its kv.wire_round sibling shows
        # the full overlapped round) — the overlap the driver buys
        # becomes VISIBLE on the merged timeline, not just a percentage
        # (docs/OBSERVABILITY.md)
        with _tr.span("fused.chunk", cat="fused", args={"chunk": j}):
            due = j - 1 - staleness
            if due in pending:
                with _tr.span("fused.adopt_wait", cat="fused",
                              args={"due": due}):
                    adopted = pending.pop(due).wait()
            else:
                adopted = None
            lo = j * chunk_size
            hi = min(num_steps, lo + chunk_size)
            with _tr.span("fused.chunk_compute", cat="fused",
                          args={"lo": lo, "hi": hi}):
                grads = dispatch_chunk(j, lo, hi, adopted)
            pending[j] = ship_chunk(j, grads)
    final = None
    for j in sorted(pending):
        with _tr.span("fused.drain_wait", cat="fused", args={"chunk": j}):
            final = pending[j].wait()
    return final


def scan_cache_lookup(cache, key):
    """Bounded-LRU lookup for compiled multi-step programs (the one
    cache policy shared by Module.run_steps and Trainer.step_k): a hit
    is re-inserted so eviction pops the least-recently-used entry —
    plain FIFO would evict the hot long-lived program, which is always
    the FIRST one inserted."""
    entry = cache.get(key)
    if entry is not None:
        cache[key] = cache.pop(key)
    return entry


def scan_cache_store(cache, key, entry):
    """Insert + bound (``MXNET_SCAN_CACHE_MAX``, default 32): a metric
    with non-primitive hyperparameters keys by object identity
    (metric._device_sig), so recreating one per epoch would otherwise
    retain a compiled scan program per instance for the process
    lifetime."""
    from .base import env
    cache[key] = entry
    while len(cache) > int(env("MXNET_SCAN_CACHE_MAX", 32)):
        cache.pop(next(iter(cache)))
    return entry


# device buffers of the last schedule per optimizer (weak-keyed so a
# dropped optimizer frees them): constant-lr training re-sends NOTHING
# per dispatch — the K-step analog of Module._lrwd_cache's discipline
# ("per-step host→device scalar transfers would dominate step latency
# on a remote-attached chip")
_SCHED_DEV_CACHE: "weakref.WeakKeyDictionary" = None  # lazy-inited


def precompute_step_schedules(opt, keys, k):
    """Advance an optimizer's HOST-side schedule state by K steps and
    return the per-step hyperparameters as scan inputs — the shared
    schedule leg of the multi-step driver (one implementation for
    Module.run_steps and Trainer.step_k, so the two can never
    de-synchronize).

    For each of the K steps, ``opt._update_count`` advances for every
    key (exactly as K eager updates would), then lr/wd are sampled —
    cheap host float math, no device sync.  Returns ``(lrs, wds, ts)``,
    each a tuple over ``keys`` of ``(k,)`` device arrays (``ts`` is the
    per-key update count for needs_t optimizers, zeros otherwise).
    Device buffers are cached per optimizer while the host values are
    unchanged, so a constant schedule costs zero transfers per call."""
    global _SCHED_DEV_CACHE
    needs_t = getattr(opt, "needs_t", False)
    n = len(keys)
    lr = np.empty((k, n), np.float32)
    wd = np.empty((k, n), np.float32)
    ts = np.zeros((k, n), np.int32)
    for j in range(k):
        for col, key in enumerate(keys):
            opt._update_count(key)
            if needs_t:
                ts[j, col] = opt._index_update_count[key]
        lr[j] = [opt._get_lr(key) for key in keys]
        wd[j] = [opt._get_wd(key) for key in keys]

    if _SCHED_DEV_CACHE is None:
        import weakref
        _SCHED_DEV_CACHE = weakref.WeakKeyDictionary()
    hkey = (tuple(keys), k, lr.tobytes(), wd.tobytes(), ts.tobytes())
    cached = _SCHED_DEV_CACHE.get(opt)
    if cached is not None and cached[0] == hkey:
        return cached[1]

    def cols(m):
        return tuple(jnp.asarray(m[:, c]) for c in range(n))

    result = (cols(lr), cols(wd), cols(ts))
    _SCHED_DEV_CACHE[opt] = (hkey, result)
    return result


@contextmanager
def schedule_rollback(opt):
    """Undo an optimizer's host-side schedule advance if the guarded
    block fails.  precompute_step_schedules moves update counts (and any
    stateful lr scheduler) K steps ahead BEFORE the scan dispatch runs;
    if the dispatch then raises (compile OOM, backend loss), the
    schedules would be K steps ahead of the actual parameter state — and
    drift further on every retry.  Wrap precompute+dispatch in this to
    keep host schedule state transactional with the device step."""
    counts = dict(opt._index_update_count)
    num_update = opt.num_update
    sched = opt.lr_scheduler
    sched_state = dict(vars(sched)) if sched is not None else None
    try:
        yield
    except BaseException:
        opt._index_update_count = counts
        opt.num_update = num_update
        if sched is not None:
            vars(sched).clear()
            vars(sched).update(sched_state)
        raise


def make_lazy_outputs(avals, make_thunk):
    """Allocate lazy output NDArrays fulfilled by ONE shared thunk.

    ``make_thunk(outs)`` receives the fresh (uninitialized) arrays and
    returns the thunk that will ``_set_data`` all of them on first read.
    Single home for the NDArray internal-construction sequence shared by
    Executor.forward and Module.run_steps' last-step outputs."""
    from .ndarray import NDArray as _ND
    outs = [_ND.__new__(_ND) for _ in avals]
    thunk = make_thunk(outs)
    for oa, av in zip(outs, avals):
        oa._handle = object()
        oa._ctx = None
        oa._grad = None
        oa._grad_req = "null"
        oa._payload = None
        oa._set_lazy(thunk, aval=av)
    return outs


def poison_stale(arr, what):
    """Permanently mark a lazy NDArray as unavailable with a clear error.

    Used after a donated fused training step consumes the buffers a pending
    thunk would need.  The poison thunk re-arms itself before raising, so
    every read fails loudly instead of only the first (NDArray._data pops
    the thunk before invoking it)."""
    def thunk():
        arr._set_lazy(thunk)  # re-arm: stay poisoned across reads
        raise MXNetError(
            f"{what} buffers were fused into the donated training step and "
            "are not materialized after update(); read them before "
            "update(), or set MXNET_FUSED_DONATE=0 / "
            "MXNET_EXEC_BULK_EXEC_TRAIN=0 to keep them live")
    arr._set_lazy(thunk)


class Executor:
    """reference: include/mxnet/executor.h:52; python/mxnet/executor.py."""

    def __init__(self, symbol: Symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None, group2ctx=None,
                 shared_exec=None, compute_dtype=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        self._compute_dtype = compute_dtype
        run, arg_names, aux_names = build_interpreter(symbol, compute_dtype)
        self._run = run
        self._arg_names = arg_names
        self._aux_names = aux_names
        self.arg_arrays = self._canon_arrays(args, arg_names, "args")
        self.aux_arrays = self._canon_arrays(aux_states, aux_names,
                                             "aux_states", allow_empty=True)
        self.grad_req = self._canon_grad_req(grad_req)
        self.grad_arrays = self._canon_grads(args_grad)
        self._monitor_callback = None
        self._monitor_all = False
        self._mesh = None
        self._arg_shardings = None   # name -> NamedSharding
        self._aux_shardings = None

        self._out_arrays: Optional[List[NDArray]] = None
        self._snapshot = None
        self._is_train = False
        self._last_key = None
        # output handles issued by forward() whose thunks still reference a
        # live snapshot — must be poisoned if a donated step consumes the
        # snapshot's buffers.  Weak refs: the executor must not keep
        # dropped outputs (and their snapshots) alive.
        self._issued_outs: List = []

        # MXNET_EXEC_BULK_EXEC_INFERENCE=0 restores per-op dispatch for
        # forward-only graphs (the reference's bulk-exec toggle): the
        # interpreter runs un-jitted, so every op is its own XLA call —
        # slower, but each intermediate is individually inspectable.
        if _base_env("MXNET_EXEC_BULK_EXEC_INFERENCE", True):
            self._jit_fwd = jax.jit(
                lambda a, x, k, t: run(a, x, k, t), static_argnums=(3,))
        else:
            self._jit_fwd = lambda a, x, k, t: run(a, x, k, t)
        self._jit_fwd_bwd = jax.jit(self._fused_fwd_bwd)

    # ------------------------------------------------------------------
    def _canon_arrays(self, arrays, names, what, allow_empty=False):
        if arrays is None:
            if allow_empty and not names:
                return []
            raise MXNetError(f"bind: {what} must be provided (or use "
                             f"simple_bind)")
        if isinstance(arrays, dict):
            missing = [n for n in names if n not in arrays]
            if missing:
                raise MXNetError(f"bind: missing {what}: {missing}")
            return [arrays[n] for n in names]
        arrays = list(arrays)
        if len(arrays) != len(names):
            raise MXNetError(f"bind: expected {len(names)} {what}, "
                             f"got {len(arrays)}")
        return arrays

    def _canon_grad_req(self, grad_req):
        names = self._arg_names
        if isinstance(grad_req, str):
            return {n: grad_req for n in names}
        if isinstance(grad_req, (list, tuple)):
            return dict(zip(names, grad_req))
        if isinstance(grad_req, dict):
            return {n: grad_req.get(n, "null") for n in names}
        raise TypeError(type(grad_req))

    def _canon_grads(self, args_grad):
        names = self._arg_names
        if args_grad is None:
            return [None] * len(names)
        if isinstance(args_grad, dict):
            return [args_grad.get(n) for n in names]
        args_grad = list(args_grad)
        if len(args_grad) != len(names):
            raise MXNetError("bind: args_grad length mismatch")
        return args_grad

    # -- dict views (reference: executor.py arg_dict etc.) --------------
    @property
    def arg_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._arg_names, self.arg_arrays))

    @property
    def grad_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._arg_names, self.grad_arrays))

    @property
    def aux_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._aux_names, self.aux_arrays))

    @property
    def output_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    @property
    def symbol(self):
        return self._symbol

    # ------------------------------------------------------------------
    @classmethod
    def simple_bind(cls, symbol: Symbol, ctx=None, grad_req="write",
                    type_dict=None, shared_exec=None, shapes=None,
                    compute_dtype=None):
        """reference: MXExecutorSimpleBind (c_api_executor.cc:219) —
        infer all shapes from the provided input shapes, allocate arg/grad/aux
        arrays, return a bound executor."""
        shapes = shapes or {}
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shapes)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        args = [nd_zeros(s, dtype=type_dict.get(n, "float32"))
                for n, s in zip(arg_names, arg_shapes)]
        aux = [nd_zeros(s, dtype=type_dict.get(n, "float32"))
               for n, s in zip(aux_names, aux_shapes)]
        ex = cls(symbol, ctx, args=args, grad_req=grad_req, aux_states=aux,
                 compute_dtype=compute_dtype)
        ex.grad_arrays = [
            nd_zeros(s, dtype=type_dict.get(n, "float32"))
            if ex.grad_req[n] != "null" else None
            for n, s in zip(arg_names, arg_shapes)]
        return ex

    def _next_key(self):
        return _rnd.key_for(self._run)

    # ------------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Lazy forward: argument *values* are captured now; outputs
        materialize on first read — and if ``backward`` runs first,
        forward+backward fuse into ONE XLA program (replacing the
        reference's op bulking, graph_executor.cc:1302)."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"forward: unknown argument {k!r}")
            pos = self._arg_names.index(k)
            if isinstance(v, NDArray):
                self.arg_arrays[pos]._set_data(v._data)
            else:
                self.arg_arrays[pos]._set_data(jnp.asarray(v))
        self._is_train = is_train
        self._last_key = self._next_key()
        # snapshot the input values: later arg mutation (or a second
        # forward) must not change what THIS forward's outputs resolve to
        snapshot = (self._arg_vals(), self._aux_vals(), self._last_key,
                    is_train)
        self._snapshot = snapshot
        out_avals = self._out_aval_list(is_train)
        out_arrays = make_lazy_outputs(
            out_avals,
            lambda outs: lambda: self._materialize(snapshot, outs))
        self._out_arrays = out_arrays
        import weakref
        self._issued_outs = [r for r in self._issued_outs
                             if (a := r()) is not None
                             and a._thunk is not None]
        self._issued_outs.extend(weakref.ref(a) for a in out_arrays)
        if self._monitor_callback is not None:
            self._materialize(snapshot, out_arrays, monitor=True)
        return self._out_arrays

    @property
    def outputs(self) -> List[NDArray]:
        if self._out_arrays is None:
            self.forward(self._is_train)
        return self._out_arrays

    def set_shardings(self, mesh, arg_pspecs, aux_pspecs=None):
        """Annotate arguments with mesh shardings (mxnet_tpu.parallel).

        Every subsequent forward/backward/fused step runs as ONE SPMD
        program over ``mesh`` — GSPMD inserts the gradient psum that the
        reference implemented as kvstore push/pull (comm.h:462) and the
        activation collectives that `group2ctx` placement implemented as
        _CrossDeviceCopy nodes (graph_executor.cc:403)."""
        from jax.sharding import NamedSharding, PartitionSpec
        self._mesh = mesh
        self._arg_shardings = {
            n: NamedSharding(mesh, arg_pspecs.get(n, PartitionSpec()))
            for n in self._arg_names}
        self._aux_shardings = {
            n: NamedSharding(mesh, (aux_pspecs or {}).get(n, PartitionSpec()))
            for n in self._aux_names}

    def _sharded(self, val, sh):
        if sh is None:
            return val
        cur = getattr(val, "sharding", None)
        if cur is not None:
            # is_equivalent_to, not ==: XLA normalizes trailing-None
            # specs (P('tp', None) comes back as P('tp')), and a false
            # mismatch here would force the host round-trip below, which
            # cannot work for process-spanning arrays
            try:
                same = cur.is_equivalent_to(sh, np.ndim(val))
            except Exception:  # noqa: BLE001 — foreign sharding types
                same = cur == sh
            if same:
                return val
        if sh.is_fully_addressable:
            return jax.device_put(val, sh)
        # mesh spans processes (multi-host SPMD): device_put cannot target
        # non-addressable shardings.  Every process feeds the same global
        # host value (the SPMD data contract — dist scripts use identical
        # seeds/batches), so build the global array from the shards THIS
        # process addresses.
        # analysis: allow(host-sync): multi-host staging — val is the HOST feed value every process supplies (SPMD data contract); the copy builds the global array, it does not read a device buffer back
        arr = np.asarray(val)
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx: arr[idx])

    def _arg_vals(self):
        if self._arg_shardings is None:
            return tuple(a._data for a in self.arg_arrays)
        return tuple(self._sharded(a._data, self._arg_shardings[n])
                     for n, a in zip(self._arg_names, self.arg_arrays))

    def _aux_vals(self):
        if self._aux_shardings is None:
            return tuple(a._data for a in self.aux_arrays)
        return tuple(self._sharded(a._data, self._aux_shardings[n])
                     for n, a in zip(self._aux_names, self.aux_arrays))

    def _out_aval_list(self, is_train):
        cache = getattr(self, "_aval_cache", None)
        if cache is None:
            cache = self._aval_cache = {}
        sig = (tuple((a.shape, str(a.dtype)) for a in self.arg_arrays),
               is_train)
        if sig not in cache:
            dummy = jax.random.PRNGKey(0)
            cache[sig] = list(jax.eval_shape(
                lambda a, x, k: self._run(a, x, k, is_train),
                self._arg_vals(), self._aux_vals(), dummy)[0])
        return cache[sig]

    def _materialize(self, snapshot, out_arrays, monitor=False):
        arg_vals, aux_vals, key, is_train = snapshot
        if monitor:
            from . import profiler as _prof
            collected = []
            with _prof.scope("executor_forward_monitored", "symbolic"):
                outs, new_aux = self._run(
                    arg_vals, aux_vals, key, is_train,
                    _collect=lambda n, os: collected.append((n, os)))
            cb = self._monitor_callback
            for n, os in collected:
                for i, o in enumerate(os):
                    nm = (n.name + "_output" if len(os) == 1
                          else f"{n.name}_output{i}")
                    cb(nm, NDArray(o))
        else:
            from . import profiler as _prof
            _prof.record_dispatch("executor.forward")
            with _prof.scope("executor_forward", "symbolic"):
                outs, new_aux = self._jit_fwd(arg_vals, aux_vals, key,
                                              is_train)
        for oa, v in zip(out_arrays, outs):
            oa._set_data(v)
        if is_train and snapshot is self._snapshot:
            for a, v in zip(self.aux_arrays, new_aux):
                a._set_data(v)

    # ------------------------------------------------------------------
    def _fused_fwd_bwd(self, arg_vals, aux_vals, key, cotangents,
                       grad_mask=None):
        """One XLA program: forward + vjp backward (+ aux updates)."""
        run = maybe_mirror(self._run)

        def f(av):
            outs, new_aux = run(av, aux_vals, key, True)
            diff = tuple(o for o in outs
                         if jnp.issubdtype(o.dtype, jnp.inexact))
            return diff, (outs, new_aux)

        diff, vjp_fn, (outs, new_aux) = jax.vjp(f, arg_vals, has_aux=True)
        grads = vjp_fn(tuple(cotangents))[0]
        need = tuple(g if self.grad_req[n] != "null" else None
                     for n, g in zip(self._arg_names, grads))
        return outs, new_aux, need

    def backward(self, out_grads=None, is_train=True):
        """Run the fused fwd+bwd program; write gradients per grad_req
        (reference: GraphExecutor::Backward, graph_executor.cc:93)."""
        if not any(r != "null" for r in self.grad_req.values()):
            raise MXNetError("backward: no gradients required "
                             "(all grad_req are null)")
        snapshot = getattr(self, "_snapshot", None)
        if snapshot is not None:
            arg_vals, aux_vals, key, _ = snapshot
        else:
            arg_vals, aux_vals = self._arg_vals(), self._aux_vals()
            key = self._last_key if self._last_key is not None \
                else self._next_key()
        out_avals = self._out_aval_list(True)
        diff_avals = [o for o in out_avals
                      if jnp.issubdtype(o.dtype, jnp.inexact)]
        if out_grads is None:
            cts = tuple(jnp.ones(o.shape, o.dtype) for o in diff_avals)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            vals = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                    for g in out_grads]
            diff_idx = [i for i, o in enumerate(out_avals)
                        if jnp.issubdtype(o.dtype, jnp.inexact)]
            cts = tuple(vals[i] for i in diff_idx)
        from . import profiler as _prof
        _prof.record_dispatch("executor.fwd_bwd")
        with _prof.scope("executor_fwd_bwd", "symbolic"):
            outs, new_aux, grads = self._jit_fwd_bwd(arg_vals, aux_vals,
                                                     key, cts)
        if self._out_arrays is None:
            self._out_arrays = [NDArray(o) for o in outs]
        else:
            for oa, v in zip(self._out_arrays, outs):
                oa._set_data(v)
        for a, v in zip(self.aux_arrays, new_aux):
            a._set_data(v)
        for name, garr, g in zip(self._arg_names, self.grad_arrays, grads):
            req = self.grad_req[name]
            if req == "null" or g is None:
                continue
            if garr is None:
                continue
            if req == "add":
                garr._set_data(garr._data + g)
            else:
                garr._set_data(g)

    # ------------------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """reference: executor.py copy_params_from."""
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._set_data(
                    arr._data if isinstance(arr, NDArray)
                    else jnp.asarray(arr))
            elif not allow_extra_params:
                raise MXNetError(f"unknown argument {name!r}")
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._set_data(
                        arr._data if isinstance(arr, NDArray)
                        else jnp.asarray(arr))
                elif not allow_extra_params:
                    raise MXNetError(f"unknown aux state {name!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new input shapes (jit recompiles per shape —
        reference: executor.py reshape)."""
        shapes = {n: tuple(a.shape) for n, a in self.arg_dict.items()}
        shapes.update({k: tuple(v) for k, v in kwargs.items()})
        new = Executor.simple_bind(self._symbol, self._ctx,
                                   grad_req=self.grad_req, shapes=shapes,
                                   compute_dtype=self._compute_dtype)
        for n, a in self.arg_dict.items():
            if n not in kwargs and n in new.arg_dict:
                if new.arg_dict[n].shape == a.shape:
                    new.arg_dict[n]._set_data(a._data)
        for n, a in self.aux_dict.items():
            if n in new.aux_dict and new.aux_dict[n].shape == a.shape:
                new.aux_dict[n]._set_data(a._data)
        if self._mesh is not None:
            # carry the sharding annotations over (pspecs are rank-generic,
            # so the same specs apply to the reshaped arrays)
            new.set_shardings(
                self._mesh,
                {n: s.spec for n, s in self._arg_shardings.items()},
                {n: s.spec for n, s in self._aux_shardings.items()})
        return new

    def set_monitor_callback(self, callback, monitor_all=False):
        """reference: GraphExecutor::SetMonitorCallback
        (graph_executor.cc:120) — per-output stats for mx.mon.Monitor."""
        self._monitor_callback = callback
        self._monitor_all = monitor_all

    def debug_str(self):
        lines = [f"Symbol outputs: {self._symbol.list_outputs()}"]
        for n in self._symbol.nodes():
            if n.is_variable:
                lines.append(f"Variable:{n.name}")
            else:
                lines.append(f"Op:{n.op}, Name={n.name}")
        return "\n".join(lines)
