"""Search spaces derived from the ``base.declare_env`` knob registry.

The registry is the ONLY source of axes: a knob becomes tunable by
declaring ``tune=`` metadata (choices or a min/max range) next to its
type, default and doc string — so the search space can never drift from
what the framework actually reads, and an undeclared knob can never be
tuned (``space_for`` raises; the ``env-knob`` lint rule additionally
flags any built-in target axis naming an unregistered knob).

Every axis knows how to sample, enumerate, perturb and ENCODE itself —
the encoding (one-hot choices, [0,1]-normalized ranges, log-scaled
where declared) is the feature vector the cost model regresses over.
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..base import MXNetError, list_env_flags, list_env_tunables


@dataclasses.dataclass(frozen=True)
class Axis:
    """One tunable knob: its registry identity plus tune metadata."""
    name: str
    typ: type
    default: object
    kind: str                      # 'choice' | 'int' | 'float'
    choices: Optional[tuple] = None
    lo: Optional[float] = None
    hi: Optional[float] = None
    log: bool = False

    # -- sampling / enumeration ---------------------------------------------
    def sample(self, rng):
        if self.kind == "choice":
            return self.choices[rng.randint(len(self.choices))]
        u = rng.uniform()
        return self._from_unit(u)

    def _from_unit(self, u: float):
        lo, hi = float(self.lo), float(self.hi)
        if self.log:
            v = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
        else:
            v = lo + u * (hi - lo)
        if self.kind == "int":
            return int(min(self.hi, max(self.lo, round(v))))
        return float(v)

    def grid(self, n: int = 5) -> tuple:
        """Deterministic candidate values: all choices, or n points
        spaced over the range (log-spaced when declared log)."""
        if self.kind == "choice":
            return tuple(self.choices)
        pts = [self._from_unit(i / (n - 1)) for i in range(n)]
        out = []
        for p in pts:           # int ranges can collapse duplicate points
            if p not in out:
                out.append(p)
        return tuple(out)

    def neighbors(self, value) -> list:
        """Adjacent values: choice index +-1, or a x2 / /2 step clipped
        to the range — the local moves the model searcher explores
        around the measured best."""
        if self.kind == "choice":
            try:
                i = self.choices.index(value)
            except ValueError:
                return [self.choices[0]]
            out = []
            if i > 0:
                out.append(self.choices[i - 1])
            if i + 1 < len(self.choices):
                out.append(self.choices[i + 1])
            return out
        out = []
        for v in (value * 0.5, value * 2.0):
            v = min(float(self.hi), max(float(self.lo), v))
            if self.kind == "int":
                v = int(round(v))
            if v != value:
                out.append(v)
        return out

    # -- features -----------------------------------------------------------
    def encode(self, value) -> List[float]:
        """Feature columns for the cost model: one-hot for choices,
        one [0,1]-normalized column for ranges."""
        if self.kind == "choice":
            row = [0.0] * len(self.choices)
            try:
                row[self.choices.index(value)] = 1.0
            except ValueError:
                pass        # unknown (e.g. imported-history) value: all-zero
            return row
        lo, hi = float(self.lo), float(self.hi)
        v = float(value)
        if self.log:
            v = max(v, lo)
            u = (math.log(v) - math.log(lo)) / (math.log(hi) - math.log(lo))
        else:
            u = (v - lo) / (hi - lo)
        return [min(1.0, max(0.0, u))]

    def width(self) -> int:
        return len(self.choices) if self.kind == "choice" else 1

    def coerce(self, raw):
        """Parse a journal/env string back to the axis's python type."""
        if self.typ is bool and isinstance(raw, str):
            return raw.lower() not in ("0", "false", "off", "")
        try:
            return self.typ(raw)
        except (TypeError, ValueError):
            return raw


def restrict_axis(axis: Axis, values: Sequence) -> Axis:
    """Narrow an axis to an explicit value list (the operator's
    chip-session move: sweep only the plausible corner).  Still
    registry-bounded: every value must sit inside the DECLARED choices
    (or range) — a restriction can never smuggle in an untunable
    setting."""
    vals = tuple(axis.coerce(v) for v in values)
    if not vals:
        raise MXNetError("autotune: empty restriction for %s" % axis.name)
    if axis.kind == "choice":
        bad = [v for v in vals if v not in axis.choices]
        if bad:
            raise MXNetError(
                "autotune: restriction values %r for %s are outside its "
                "declared choices %r" % (bad, axis.name, axis.choices))
    else:
        bad = [v for v in vals
               if not (float(axis.lo) <= float(v) <= float(axis.hi))]
        if bad:
            raise MXNetError(
                "autotune: restriction values %r for %s are outside its "
                "declared range [%r, %r]"
                % (bad, axis.name, axis.lo, axis.hi))
    return Axis(name=axis.name, typ=axis.typ, default=axis.default,
                kind="choice", choices=vals)


def axis_for(name: str) -> Axis:
    """The Axis for one registered knob; raises for undeclared or
    tune-less knobs — the 'undeclared knobs can never be tuned' gate."""
    flags = list_env_flags()
    if name not in flags:
        raise MXNetError(
            "autotune: knob %s is not declared via base.declare_env — "
            "undeclared knobs can never be tuned" % name)
    typ, default, _doc = flags[name]
    tune = list_env_tunables().get(name)
    if tune is None:
        raise MXNetError(
            "autotune: knob %s is declared but carries no tune= "
            "metadata — declare its choices or min/max range to make "
            "it sweepable" % name)
    if tune["kind"] == "choice":
        return Axis(name=name, typ=typ, default=default, kind="choice",
                    choices=tuple(tune["choices"]))
    return Axis(name=name, typ=typ, default=default, kind=tune["kind"],
                lo=tune["min"], hi=tune["max"], log=tune["log"])


class SearchSpace:
    """An ordered set of axes; configs are {env name: value} dicts."""

    def __init__(self, axes: Sequence[Axis]):
        if not axes:
            raise MXNetError("autotune: empty search space")
        self.axes: Dict[str, Axis] = {a.name: a for a in axes}

    def __len__(self):
        return len(self.axes)

    # -- configs ------------------------------------------------------------
    def default_config(self) -> dict:
        return {n: a.default for n, a in self.axes.items()}

    def sample(self, rng) -> dict:
        return {n: a.sample(rng) for n, a in self.axes.items()}

    def grid(self, n: int = 5) -> Iterator[dict]:
        """Cartesian product of per-axis grids, in declaration order."""
        names = list(self.axes)
        per_axis = [self.axes[name].grid(n) for name in names]
        for combo in itertools.product(*per_axis):
            yield dict(zip(names, combo))

    def neighbors(self, config: dict) -> List[dict]:
        """One-axis-changed variants of ``config``."""
        out = []
        for name, axis in self.axes.items():
            for v in axis.neighbors(config.get(name, axis.default)):
                cand = dict(config)
                cand[name] = v
                out.append(cand)
        return out

    def canonical(self, config: dict) -> Tuple:
        """Hashable identity for dedup across proposals/journal resume
        (axis order fixed; values coerced through the axis type so a
        journal round trip — json stringification — cannot split one
        config into two identities)."""
        return tuple((n, a.coerce(config.get(n, a.default)))
                     for n, a in self.axes.items())

    def encode(self, config: dict) -> List[float]:
        row: List[float] = []
        for n, a in self.axes.items():
            row.extend(a.encode(a.coerce(config.get(n, a.default))))
        return row

    def feature_width(self) -> int:
        return sum(a.width() for a in self.axes.values())

    def size(self) -> Optional[int]:
        """Config count for all-choice spaces, None for continuous."""
        total = 1
        for a in self.axes.values():
            if a.kind != "choice":
                return None
            total *= len(a.choices)
        return total


def space_for(knob_names: Sequence[str],
              restrict: Optional[Dict[str, Sequence]] = None) \
        -> SearchSpace:
    """Build the space for an explicit knob list (a target's axes),
    optionally narrowing axes to explicit value lists."""
    restrict = restrict or {}
    unknown = set(restrict) - set(knob_names)
    if unknown:
        raise MXNetError("autotune: restriction names %s are not axes "
                         "of this space %s"
                         % (sorted(unknown), list(knob_names)))
    axes = []
    for n in knob_names:
        a = axis_for(n)
        if n in restrict:
            a = restrict_axis(a, restrict[n])
        axes.append(a)
    return SearchSpace(axes)


def tunable_names() -> List[str]:
    """Every registered knob carrying tune metadata."""
    return sorted(list_env_tunables())
