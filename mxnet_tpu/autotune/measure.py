"""Measurement executors: run one config in a fresh subprocess.

The ``fresh_process_probe`` discipline (benchmark/_bench_common.py)
applied to whole trials: every measurement runs in its OWN child
process with a hard deadline — a config that hangs (the BENCH_r02–r05
stuck-tunnel shape), OOMs, or crashes is killed/recorded and the sweep
moves on; nothing a trial does can wedge the harness.  The child's
whole process GROUP is SIGKILLed on timeout because targets like the
launcher-driven smokes spawn their own children.

Contract with targets: the child prints ONE JSON object line on stdout
(the bench.py output contract); stderr/progress marks are free-form.
The LAST parseable JSON-object line wins, matching bench.py's
single-line guarantee while tolerating chatty targets.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional


@dataclasses.dataclass
class MeasureResult:
    status: str                    # ok | timeout | crash | error
    payload: Optional[dict]        # the parsed JSON line (None unless found)
    duration_s: float
    error: Optional[str] = None


def _last_json_line(text: str) -> Optional[dict]:
    out = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict):
            out = d
    return out


class SubprocessExecutor:
    """Run target commands with per-trial env overrides and a deadline."""

    def __init__(self, timeout_s: float, mark=None):
        self.timeout_s = max(1.0, float(timeout_s))
        self._mark = mark or (lambda msg: None)

    def run(self, argv: List[str], env_overrides: Dict[str, object],
            cwd: Optional[str] = None) -> MeasureResult:
        env = dict(os.environ)
        for k, v in env_overrides.items():
            env[k] = str(v)
        t0 = time.perf_counter()
        try:
            proc = subprocess.Popen(
                argv, cwd=cwd, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                start_new_session=True)   # own group: killpg reaps children
        except OSError as e:
            return MeasureResult(status="crash", payload=None,
                                 duration_s=0.0,
                                 error="spawn failed: %s" % e)
        try:
            out, _ = proc.communicate(timeout=self.timeout_s)
        except subprocess.TimeoutExpired:
            self._kill_group(proc)
            try:
                out, _ = proc.communicate(timeout=10)
            except Exception:  # noqa: BLE001 — already SIGKILLed; best effort
                out = b""
            dt = time.perf_counter() - t0
            return MeasureResult(
                status="timeout", payload=_last_json_line(
                    (out or b"").decode(errors="replace")),
                duration_s=dt,
                error="trial deadline %.0fs exceeded — process group "
                      "SIGKILLed" % self.timeout_s)
        dt = time.perf_counter() - t0
        text = (out or b"").decode(errors="replace")
        payload = _last_json_line(text)
        if proc.returncode != 0:
            return MeasureResult(
                status="crash", payload=payload, duration_s=dt,
                error="rc=%s: %s" % (proc.returncode,
                                     text.strip()[-400:] or "<no output>"))
        if payload is None:
            return MeasureResult(
                status="error", payload=None, duration_s=dt,
                error="no JSON line on stdout (output contract): %s"
                      % (text.strip()[-400:] or "<no output>"))
        if payload.get("error"):
            return MeasureResult(status="error", payload=payload,
                                 duration_s=dt,
                                 error=str(payload["error"])[:400])
        return MeasureResult(status="ok", payload=payload, duration_s=dt)

    @staticmethod
    def _kill_group(proc) -> None:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            proc.kill()


def python_argv(*tail: str) -> List[str]:
    """argv prefix for a child running THIS interpreter."""
    return [sys.executable, *tail]
