"""The built-in measurement targets.

A target = (the knobs it sweeps, the command that measures one config,
which payload key is the objective and its sign, and how a winning
config maps into the per-topology BENCH_DEFAULTS.json entry).  The
knobs MUST be registered via ``base.declare_env`` with tune metadata —
``space_for`` raises otherwise, and the ``env-knob`` lint rule flags
any built-in axis naming an unregistered knob (tunable-but-undeclared).

Every command is a fresh subprocess obeying the one-JSON-line stdout
contract (measure.SubprocessExecutor parses the last JSON object
line).  The config rides ONLY in environment variables — exactly the
surface the framework reads the knobs from, so a measured win is by
construction the setting a real run would use.
"""
from __future__ import annotations

import dataclasses
import os
import sys
from typing import Dict, List, Optional, Tuple

from ..base import MXNetError
from .space import SearchSpace, space_for


def repo_root() -> str:
    """The checkout root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Target:
    name: str
    knobs: Tuple[str, ...]
    objective: str               # payload key carrying the objective
    maximize: bool
    doc: str
    # env knob -> flat BENCH_DEFAULTS key bench.py resolves directly;
    # knobs NOT mapped here promote under the entry's "env" dict and are
    # os.environ.setdefault-ed by the consumer for that topology
    defaults_map: Tuple[Tuple[str, str], ...] = ()
    module: Optional[str] = None     # python -m entry
    script: Optional[str] = None     # repo-root-relative script

    def command(self) -> List[str]:
        if self.module:
            return [sys.executable, "-m", self.module]
        return [sys.executable, os.path.join(repo_root(), self.script)]

    def space(self, restrict=None) -> SearchSpace:
        return space_for(self.knobs, restrict=restrict)

    def objective_value(self, payload: dict) -> Optional[float]:
        v = payload.get(self.objective)
        try:
            return float(v) if v is not None else None
        except (TypeError, ValueError):
            return None

    def defaults_entry(self, config: dict) -> dict:
        """Project a winning config into the per-topology defaults
        entry shape: mapped knobs become bench.py's flat keys, the rest
        land under "env"."""
        mapped = dict(self.defaults_map)
        entry: dict = {}
        env: dict = {}
        for knob, value in config.items():
            if knob in mapped:
                entry[mapped[knob]] = value
            else:
                env[knob] = value
        if env:
            entry["env"] = env
        return entry


TARGETS: Dict[str, Target] = {t.name: t for t in [
    Target(
        name="stub",
        knobs=("MXNET_KVSTORE_WINDOW", "MXNET_KVSTORE_FUSED_CHUNK"),
        objective="value", maximize=True,
        doc="deterministic CPU stub backend (stub_target.py): a known "
            "analytic bowl over two real registry knobs — exercises the "
            "whole propose/measure/journal/promote loop in tier-1 with "
            "no chip, no jax import, sub-second trials",
        # stdlib-only child run by PATH on purpose: `-m` would import
        # the full mxnet_tpu package (jax) for a 50 ms trial
        script="mxnet_tpu/autotune/stub_target.py"),
    Target(
        name="bench",
        knobs=("BENCH_BATCH", "BENCH_DTYPE", "BENCH_OPT",
               "BENCH_STEPS_PER_CALL", "BENCH_STEM", "BENCH_LAYOUT",
               "BENCH_REMAT"),
        objective="value", maximize=True,
        doc="bench.py ResNet-50 fused-step throughput (imgs/sec) — the "
            "queued steps-per-call x batch x remat x layout sweep from "
            "PERF_NOTES rounds 6-10",
        defaults_map=(("BENCH_BATCH", "batch"),
                      ("BENCH_DTYPE", "dtype"),
                      ("BENCH_OPT", "opt"),
                      ("BENCH_STEPS_PER_CALL", "steps_per_call"),
                      ("BENCH_STEM", "stem"),
                      ("BENCH_LAYOUT", "layout"),
                      ("BENCH_REMAT", "remat")),
        script="bench.py"),
    Target(
        name="serving",
        knobs=("MXNET_SERVING_BUCKETS", "MXNET_SERVING_MAX_WAIT_MS",
               "MXNET_SERVING_QUEUE_DEPTH",
               "MXNET_SERVING_CLIENT_WINDOW"),
        objective="p99_ms", maximize=False,
        doc="serving_probe.py: in-process replica + pipelined client, "
            "request storm, p50/p99/QPS from the serving_stats "
            "envelope — the serving latency/QPS row of the roadmap",
        module="mxnet_tpu.autotune.serving_probe"),
    Target(
        name="failover",
        knobs=("MXNET_KVSTORE_SNAPSHOT_S", "MXNET_KVSTORE_WINDOW"),
        objective="failover_rebuild_s", maximize=False,
        doc="failover_probe.py: elastic pair + worker, the COORDINATOR "
            "killed mid-job at the faultinject boundary, rebuild cost "
            "from the kvstore.failover_rebuild_s gauge — the elastic "
            "handoff/failover cost curve vs snapshot cadence",
        module="mxnet_tpu.autotune.failover_probe"),
]}


def get_target(name: str) -> Target:
    try:
        return TARGETS[name]
    except KeyError:
        raise MXNetError("autotune: unknown target %r; built-ins: %s"
                         % (name, sorted(TARGETS)))


def all_target_knobs() -> Dict[str, List[str]]:
    """{target name: knob names} — the env-knob lint rule checks every
    entry against the declare_env registry (tunable-but-undeclared)."""
    return {name: list(t.knobs) for name, t in TARGETS.items()}
