"""The append-only, resumable trials journal (JSONL).

One line per measured trial.  Append-only is the resume contract: a
sweep killed mid-trial loses at most the line being written — ``load``
tolerates a truncated trailing line, and the searcher's dedup over
``(target, canonical config)`` means re-running the same command
simply continues where the dead sweep stopped.  No rewriting, ever:
imported history, failed trials and timeouts all stay on the record
(the cost model filters by status; a timeout is itself a data point a
future searcher can learn to avoid).
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import List, Optional

SCHEMA = 1


@dataclasses.dataclass
class Trial:
    num: int                    # 1-based position in THIS journal
    target: str                 # targets.TARGETS key
    config: dict                # {env knob name: value}
    status: str                 # ok | timeout | crash | error
    objective: Optional[float]  # raw objective (sign per target), None unless ok
    metrics: dict = dataclasses.field(default_factory=dict)
    duration_s: Optional[float] = None
    error: Optional[str] = None
    source: str = "measured"    # 'measured' or the imported-history file
    ts: Optional[float] = None

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["schema"] = SCHEMA
        return d

    @classmethod
    def from_json(cls, d: dict) -> "Trial":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    @property
    def ok(self) -> bool:
        return self.status == "ok" and self.objective is not None


class Journal:
    def __init__(self, path: str):
        self.path = path

    def load(self) -> List[Trial]:
        """All parseable trials, in order.  A truncated/corrupt line
        (the killed-mid-write case) is skipped, not fatal — resume must
        work from exactly the file a dead sweep left behind."""
        out: List[Trial] = []
        if not os.path.exists(self.path):
            return out
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if isinstance(d, dict) and "target" in d:
                    try:
                        out.append(Trial.from_json(d))
                    except TypeError:
                        continue
        return out

    def append(self, trial: Trial) -> Trial:
        if trial.ts is None:
            trial.ts = time.time()
        d = os.path.dirname(os.path.abspath(self.path))
        if d:
            os.makedirs(d, exist_ok=True)
        # a sweep killed mid-append leaves a TORN line with no trailing
        # newline — the next record must start on a fresh line or the
        # concatenation corrupts BOTH lines
        lead = ""
        try:
            with open(self.path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b"\n":
                    lead = "\n"
        except OSError:
            pass   # absent or empty file: no repair needed
        with open(self.path, "a") as f:
            f.write(lead + json.dumps(trial.to_json()) + "\n")
            f.flush()
            os.fsync(f.fileno())
        return trial

    def next_num(self) -> int:
        trials = self.load()
        return (max((t.num for t in trials), default=0)) + 1

    def sources(self) -> set:
        return {t.source for t in self.load()}
