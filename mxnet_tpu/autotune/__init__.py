"""mxnet_tpu.autotune — the measure-and-search harness over the knob
registry (docs/AUTOTUNE.md).

TVM-style propose → measure → update loop (arXiv:1802.04799) with a
fit-on-the-fly cost model in the TpuGraphs spirit (arXiv:2308.13490):

* :mod:`space`   — search spaces derived EXCLUSIVELY from the
  ``base.declare_env`` registry's ``tune=`` metadata: an undeclared
  knob can never be tuned (and a target axis naming one is an
  ``env-knob`` lint finding);
* :mod:`measure` — subprocess executors with the
  ``fresh_process_probe`` deadline/kill discipline: a hung trial is
  SIGKILLed (whole process group) and recorded, never serializing the
  sweep;
* :mod:`targets` — the built-in measurement targets: ``bench``
  (bench.py throughput), ``serving`` (p99/QPS via serving_stats),
  ``failover`` (elastic coordinator-kill rebuild cost), and ``stub``
  (deterministic CPU backend that makes the whole loop tier-1-testable
  before a chip session ever runs);
* :mod:`search` / :mod:`model` — random/grid baselines plus the
  epsilon-greedy model searcher over a ridge regressor, seeded so the
  same journal + seed reproduce the same proposal;
* :mod:`journal` — the append-only resumable JSONL trials journal;
* :mod:`promote` — winners banked into the per-topology
  BENCH_DEFAULTS.json schema (device kind x host count x worker/server
  count) that bench.py loads for that topology and only that topology;
* :mod:`history` — seed-import of the banked BENCH_r0*.json rounds and
  BENCH_LOG.jsonl so the cost model starts warm.

Entry point: ``python -m mxnet_tpu.autotune`` (see ``--help``).
"""
from .journal import Journal, Trial                      # noqa: F401
from .measure import MeasureResult, SubprocessExecutor   # noqa: F401
from .model import CostModel                             # noqa: F401
from .promote import (load_defaults, lookup_defaults,    # noqa: F401
                      promote, topology_key)
from .search import make_searcher                        # noqa: F401
from .space import Axis, SearchSpace, space_for          # noqa: F401
from .targets import TARGETS, Target, get_target         # noqa: F401
