"""``python -m mxnet_tpu.autotune`` — the sweep driver.

Propose → measure (fresh subprocess, deadline) → journal → refit, for
``--trials`` rounds; then promote the measured-best config into the
per-topology BENCH_DEFAULTS.json entry for the topology the
measurements actually ran on.  Resumable by construction: the journal
is append-only and proposals are a pure function of (journal, seed),
so re-running the same command after a kill continues the sweep —
measured configs are never re-proposed.

Prints exactly ONE JSON summary line on stdout (the bench.py output
contract); progress marks go to stderr.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

from ..base import env
from .history import import_history
from .journal import Journal, Trial
from .measure import SubprocessExecutor
from .promote import promote, topology_key
from .search import make_searcher
from .targets import TARGETS, get_target, repo_root


def _mark(msg: str) -> None:
    print("[autotune] %s" % msg, file=sys.stderr, flush=True)


def _trial_metrics(payload) -> dict:
    if not isinstance(payload, dict):
        return {}
    return {k: v for k, v in payload.items()
            if isinstance(v, (int, float, str, bool)) or v is None}


def _topology_for(trial: Trial) -> str:
    m = trial.metrics or {}
    # bench.py already computed its own topology (incl. DMLC worker/
    # server counts the payload does not spell out separately) — trust
    # it over re-deriving with single-process defaults
    if m.get("topology"):
        return m["topology"]
    return topology_key(m.get("device"),
                        hosts=m.get("hosts", 1),
                        workers=m.get("workers", 1),
                        servers=m.get("servers", 0))


def _effective_config(target, space, config: dict, payload) -> dict:
    """The config the trial REALLY measured.  bench.py may legally
    deviate from the proposed one (OOM halves the batch) — when the
    payload reports a different, still-declared value for a mapped
    knob, journal that value: the cost model must not attribute batch
    512's throughput to batch 1024, and promotion must never bank an
    always-OOM setting."""
    if not isinstance(payload, dict):
        return config
    out = dict(config)
    for knob, key in target.defaults_map:
        if knob not in out or payload.get(key) is None:
            continue
        axis = space.axes.get(knob)
        if axis is None:
            continue
        eff = axis.coerce(payload[key])
        if eff == axis.coerce(out[knob]):
            continue
        # adopt only values the axis itself could have proposed (e.g.
        # bench reports remat=False for the "0" choice — not a value)
        if axis.kind != "choice" or eff in axis.choices:
            out[knob] = eff
    return out


def main(argv=None) -> int:
    root = repo_root()
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.autotune",
        description="measure-and-search over the declared knob "
                    "registry (docs/AUTOTUNE.md)")
    ap.add_argument("--target", default="stub", choices=sorted(TARGETS),
                    help="what to measure (default: stub)")
    ap.add_argument("--trials", type=int,
                    default=env("MXNET_AUTOTUNE_TRIALS"),
                    help="measured trials this run")
    ap.add_argument("--seed", type=int,
                    default=env("MXNET_AUTOTUNE_SEED"))
    ap.add_argument("--strategy", default=env("MXNET_AUTOTUNE_STRATEGY"),
                    choices=("model", "random", "grid"))
    ap.add_argument("--epsilon", type=float,
                    default=env("MXNET_AUTOTUNE_EPSILON"))
    ap.add_argument("--candidates", type=int,
                    default=env("MXNET_AUTOTUNE_CANDIDATES"))
    ap.add_argument("--timeout-s", type=float,
                    default=env("MXNET_AUTOTUNE_TRIAL_TIMEOUT_S"),
                    help="hard per-trial deadline (SIGKILL + journal "
                         "status=timeout)")
    ap.add_argument("--journal", default=None,
                    help="trials journal path (default: "
                         "<repo>/autotune_trials.jsonl)")
    ap.add_argument("--defaults", default=None,
                    help="promoted-defaults path (default: "
                         "<repo>/BENCH_DEFAULTS.json)")
    ap.add_argument("--topology", default=None,
                    help="override the promotion topology key "
                         "(default: derived from the best trial's "
                         "device/hosts/workers/servers fields)")
    ap.add_argument("--restrict", action="append", default=[],
                    metavar="KNOB=v1,v2,...",
                    help="narrow one axis to an explicit value list "
                         "(repeatable; values must sit inside the "
                         "knob's DECLARED choices/range — the "
                         "chip-session move for sweeping one corner)")
    ap.add_argument("--no-promote", action="store_true",
                    help="measure and journal only")
    ap.add_argument("--import-history", action="store_true",
                    help="seed-import BENCH_LOG.jsonl + BENCH_r0*.json "
                         "into the journal and exit")
    args = ap.parse_args(argv)

    journal = Journal(args.journal or
                      ("%s/autotune_trials.jsonl" % root))
    defaults_path = args.defaults or ("%s/BENCH_DEFAULTS.json" % root)

    if args.import_history:
        counts = import_history(journal, root)
        print(json.dumps({"metric": "autotune_import",
                          "journal": journal.path,
                          "imported": counts,
                          "total": sum(counts.values())}))
        return 0

    target = get_target(args.target)
    restrict = {}
    for spec in args.restrict:
        knob, _, vals = spec.partition("=")
        if not vals:
            ap.error("--restrict wants KNOB=v1,v2,..., got %r" % spec)
        restrict[knob] = vals.split(",")
    space = target.space(restrict=restrict)
    searcher = make_searcher(args.strategy, space, target.maximize,
                             args.seed, epsilon=args.epsilon,
                             candidates=args.candidates)
    executor = SubprocessExecutor(args.timeout_s, mark=_mark)
    _mark("target=%s axes=%s strategy=%s trials=%d journal=%s"
          % (target.name, list(space.axes), args.strategy, args.trials,
             journal.path))

    # one parse up front; appends maintain the in-memory view (a
    # history-warmed journal is thousands of lines — re-parsing it per
    # trial would be quadratic)
    all_trials = journal.load()
    past = [t for t in all_trials if t.target == target.name]
    num = max((t.num for t in all_trials), default=0)
    ran = 0
    measured_now = []
    for _ in range(max(0, args.trials)):
        config = searcher.propose(past)
        _mark("trial %d: %s" % (len(past) + 1, config))
        t0 = time.time()
        res = executor.run(target.command(), config)
        objective = (target.objective_value(res.payload)
                     if res.status == "ok" else None)
        status = res.status
        if status == "ok" and objective is None:
            status = "error"
        num += 1
        trial = journal.append(Trial(
            num=num, target=target.name,
            config=_effective_config(target, space, config, res.payload),
            status=status, objective=objective,
            metrics=_trial_metrics(res.payload),
            duration_s=round(res.duration_s, 3), error=res.error,
            source="measured", ts=t0))
        past.append(trial)
        measured_now.append(trial)
        ran += 1
        _mark("trial done: status=%s objective=%s (%.1fs)"
              % (status, objective, res.duration_s))

    ok = [t for t in past if t.ok]
    key = (lambda t: t.objective) if target.maximize \
        else (lambda t: -t.objective)
    # promotion is strictly per topology: pick THE topology this run
    # measured (or --topology), then the best ok trial OF that topology
    # — an imported other-device row must neither become "the winner"
    # for hardware it never ran on nor hysteresis-shadow the topology
    # this sweep actually measured
    topology = args.topology
    if topology is None:
        now_ok = [t for t in measured_now if t.ok]
        if now_ok:
            topology = _topology_for(now_ok[-1])
        elif ok:
            topology = _topology_for(max(ok, key=key))
    cand = [t for t in ok
            if topology is None or _topology_for(t) == topology]
    best = max(cand, key=key) if cand else None

    promoted = False
    if best is not None:
        if not args.no_promote:
            promoted = promote(
                defaults_path, topology, target.defaults_entry(best.config),
                best.objective, maximize=target.maximize,
                provenance={"target": target.name,
                            "objective": target.objective,
                            "metric": best.metrics.get("metric"),
                            "device": best.metrics.get("device"),
                            "trial": best.num, "ts": best.ts,
                            "journal": journal.path})
            _mark("promotion %s for %s"
                  % ("WROTE %s" % defaults_path if promoted
                     else "skipped (hysteresis)", topology))

    print(json.dumps({
        "metric": "autotune_sweep",
        "target": target.name,
        "strategy": args.strategy,
        "trials_run": ran,
        "trials_total": len(past),
        "ok": len(ok),
        "best_objective": best.objective if best else None,
        "best_config": best.config if best else None,
        "topology": topology,
        "promoted": promoted,
        "journal": journal.path,
        "defaults": defaults_path,
    }))
    return 0 if best is not None or args.trials == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
