"""Failover measurement target: elastic coordinator-kill rebuild cost.

The in-process twin of the CI coordinator-failover smoke, instrumented
as a measurement: two elastic servers + one worker, keys striped
across both, then the COORDINATOR is stopped mid-job — the worker
elects the successor, the ledger rebuilds, the three-phase handoff
re-stripes, and the probe reports the ``kvstore.failover_rebuild_s``
gauge (the successor's rebuild clock) plus the worker-observed repair
wall time.  This is the roadmap's handoff/failover cost curve: sweep
MXNET_KVSTORE_SNAPSHOT_S (cadence) x MXNET_KVSTORE_WINDOW and see what
cadence actually buys at repair time.

Objective key: ``failover_rebuild_s`` (minimize).  Run under
JAX_PLATFORMS=cpu for a chip-independent number — the cost is host/
wire-bound (ledger rebuild + restripe + re-push), not compute.
"""
from __future__ import annotations

import json
import os
import sys
import time


def _rig_env() -> None:
    """Fixed rig knobs — setdefault so the SWEPT knobs (snapshot
    cadence, window) ride in from the executor untouched."""
    for name, val in (
            ("MXNET_KVSTORE_ELASTIC", "1"),
            ("MXNET_KVSTORE_RETRY_MAX", "3"),
            ("MXNET_KVSTORE_RETRY_INITIAL_MS", "10"),
            ("MXNET_KVSTORE_RETRY_MAX_MS", "100"),
            ("MXNET_KVSTORE_HEARTBEAT_INTERVAL", "0.1"),
            ("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", "0.5"),
            ("MXNET_KVSTORE_BIGARRAY_BOUND", "1024"),
            ("DMLC_NUM_WORKER", "1"),
            ("DMLC_WORKER_ID", "0")):
        os.environ.setdefault(name, val)


def main() -> int:
    _rig_env()
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu.kvstore_server import KVStoreServer

    rows = int(os.environ.get("MXT_AUTOTUNE_FAILOVER_ROWS", "4096"))
    snapshot_s = float(os.environ.get("MXNET_KVSTORE_SNAPSHOT_S", "0"))

    srv0 = KVStoreServer(server_id=0, num_workers=1, elastic=True)
    srv1 = KVStoreServer(server_id=1, num_workers=1, elastic=True)
    uris = "127.0.0.1:%d,127.0.0.1:%d" % (srv0.port, srv1.port)
    os.environ["MXT_SERVER_URIS"] = uris
    for srv in (srv0, srv1):
        srv._roster_servers = uris.split(",")
        srv._snapshot_s = snapshot_s
    srv0.start_background()
    srv1.start_background()
    kv = mx.kv.create("dist_async")
    try:
        big = np.arange(rows * 32, dtype=np.float32).reshape(rows, 32)
        kv.init("big", mx.nd.NDArray(big))
        kv.init("small", mx.nd.ones((4, 4)))
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=0.125, momentum=0.9, wd=0.0, rescale_grad=1.0))
        kv.push("big", mx.nd.ones((rows, 32)))
        kv.push("small", mx.nd.ones((4, 4)))
        out_b, out_s = mx.nd.zeros((rows, 32)), mx.nd.zeros((4, 4))
        kv.pull("big", out=out_b)      # sync point: pull cache = state
        kv.pull("small", out=out_s)
        if snapshot_s > 0:             # let at least one snapshot beat land
            time.sleep(min(2.0, 2.5 * snapshot_s))

        t0 = time.perf_counter()
        srv0.stop()                    # the COORDINATOR dies
        # the next round rides succession + repair end to end
        kv.push("big", mx.nd.ones((rows, 32)))
        kv.push("small", mx.nd.ones((4, 4)))
        kv.barrier()
        kv.pull("big", out=out_b)
        kv.pull("small", out=out_s)
        repair_wall_s = time.perf_counter() - t0

        counts = profiler.channel_counts()
        rebuild = counts.get("kvstore.failover_rebuild_s")
        import jax
        out = {
            "metric": "kvstore_failover_rebuild_s",
            "value": rebuild,
            "unit": "s",
            "failover_rebuild_s": rebuild,
            "repair_wall_s": round(repair_wall_s, 4),
            "failovers": counts.get("kvstore.coordinator_failover", 0),
            "rows": rows,
            "snapshot_s": snapshot_s,
            "window": int(os.environ.get("MXNET_KVSTORE_WINDOW", "8")),
            "device": jax.devices()[0].device_kind,
            "workers": 1, "servers": 2,   # the probe's topology
        }
        if rebuild is None:
            out["error"] = ("no kvstore.failover_rebuild_s gauge — "
                            "failover never ran")
        print(json.dumps(out))
        return 0 if out.get("error") is None else 1
    finally:
        try:
            kv.close(stop_servers=True)
        except Exception:  # noqa: BLE001 — teardown after a kill probe
            pass
        srv0.stop()
        srv1.stop()


if __name__ == "__main__":
    sys.exit(main())
