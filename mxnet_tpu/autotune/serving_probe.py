"""Serving measurement target: p99 / QPS of one replica under a storm.

Stands up an in-process ServingReplica + pipelined ServingClient with
the serving knobs taken straight from the environment (exactly how a
production replica reads them — the sweep's config IS the env), fires
a mixed-batch request storm, and prints the one-JSON-line measurement
from the ``serving_stats`` latency counters: p50/p99 (nearest-rank over
the profiler ring) and QPS.  BUSY sheds are retried like a production
client would — a queue-depth config that sheds pays for it in latency,
not in a probe crash.

Objective key: ``p99_ms`` (minimize).  Swept knobs:
MXNET_SERVING_BUCKETS / _MAX_WAIT_MS / _QUEUE_DEPTH / _CLIENT_WINDOW.
"""
from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.serving import BusyError, ServingClient, ServingReplica

    feat, hidden = 32, 8
    requests = int(os.environ.get("MXT_AUTOTUNE_SERVING_REQUESTS", "192"))

    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc")
    sym = mx.sym.SoftmaxOutput(fc, name="softmax")
    rs = np.random.RandomState(0)
    params = {
        "fc_weight": mx.nd.NDArray(rs.randn(hidden, feat)
                                   .astype(np.float32)),
        "fc_bias": mx.nd.NDArray(rs.randn(hidden).astype(np.float32)),
    }

    # buckets / max_wait / queue_depth resolve from the env inside the
    # replica; the client window from MXNET_SERVING_CLIENT_WINDOW
    rep = ServingReplica(sym, {"data": (feat,)}, params)
    rep.start_background()
    cli = ServingClient("127.0.0.1:%d" % rep.port)
    try:
        x = rs.randn(8, feat).astype(np.float32)
        futs = []
        for i in range(requests):
            rows = 1 + (i % 8)
            req = x[:rows]
            for _ in range(64):          # BUSY = retryable, not fatal
                try:
                    futs.append(cli.predict_async(req))
                    break
                except BusyError:
                    time.sleep(0.002)
            else:
                raise RuntimeError("shed on every retry — queue depth "
                                   "config starves the probe")
        for fut in futs:
            fut.get()
        st = cli.stats()
        lat = st.get("latency") or {}
        import jax
        out = {
            "metric": "serving_p99_ms",
            "value": lat.get("p99_ms"),
            "unit": "ms",
            "p50_ms": lat.get("p50_ms"),
            "p99_ms": lat.get("p99_ms"),
            "qps": lat.get("qps"),
            "requests": len(futs),
            "batches": st.get("batches"),
            "shed": st.get("shed"),
            "device": jax.devices()[0].device_kind,
            "workers": 1, "servers": 1,   # one client, one replica
        }
        if out["value"] is None:
            out["error"] = "serving_stats returned no latency window"
        print(json.dumps(out))
        return 0 if out.get("error") is None else 1
    finally:
        cli.close()
        rep.stop()


if __name__ == "__main__":
    sys.exit(main())
