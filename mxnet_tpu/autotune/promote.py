"""Per-topology promoted defaults (BENCH_DEFAULTS.json, schema 2).

The seed repo's file was one flat dict — the best config of whatever
chip last ran, applied to EVERY later run: a b256-TPU winner would
silently become the CPU smoke's batch, and a MULTICHIP promotion would
clobber the single-chip row.  Schema 2 keys every entry by TOPOLOGY —
device kind x host count x worker/server count — and consumers look up
exactly their own topology (and only it):

    {"schema": 2,
     "topologies": {
       "TPU v5 lite|hosts=1|n=1|s=0": {
         "batch": 256, "dtype": "bfloat16", ...,     # bench.py keys
         "env": {"MXNET_KVSTORE_WINDOW": 8, ...},    # knob setdefaults
         "promoted_from": {...}}}}                   # provenance

Back-compat: a legacy flat file is read as ONE topology keyed by its
``promoted_from.device`` (the only provenance it carried) — so the old
TPU-v5e entry still applies to TPU-v5e runs and no longer leaks
anywhere else.  Promotion keeps the >2% hysteresis per topology (noise
must not flip defaults) and is strictly per-key: promoting a MULTICHIP
row can never touch the single-chip one.

Stdlib-only on purpose: bench.py and tools/ import this before/without
a healthy backend.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

SCHEMA = 2
_UNKNOWN_DEVICE = "unknown-device"


def topology_key(device: str, hosts: int = 1, workers: int = 1,
                 servers: int = 0) -> str:
    """The canonical topology identity a measurement/consumer runs in."""
    return "%s|hosts=%d|n=%d|s=%d" % (
        device or _UNKNOWN_DEVICE, int(hosts), int(workers), int(servers))


def _migrate_flat(doc: dict) -> dict:
    """View a legacy flat defaults dict as a one-topology schema-2 doc."""
    device = (doc.get("promoted_from") or {}).get("device") \
        or _UNKNOWN_DEVICE
    return {"schema": SCHEMA,
            "topologies": {topology_key(device): dict(doc)}}


def load_defaults(path: str) -> dict:
    """The schema-2 doc at ``path`` ({} topologies when absent/corrupt);
    legacy flat files are migrated in-memory."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {"schema": SCHEMA, "topologies": {}}
    if not isinstance(doc, dict):
        return {"schema": SCHEMA, "topologies": {}}
    if isinstance(doc.get("topologies"), dict):
        return {"schema": SCHEMA, "topologies": dict(doc["topologies"])}
    if doc:
        return _migrate_flat(doc)
    return {"schema": SCHEMA, "topologies": {}}


def lookup_defaults(path: str, topology: Optional[str]) -> dict:
    """The promoted entry for EXACTLY ``topology`` ({} when absent or
    topology is None — an unknown device gets no promoted config, which
    is the whole point)."""
    if not topology:
        return {}
    entry = load_defaults(path)["topologies"].get(topology)
    return dict(entry) if isinstance(entry, dict) else {}


def promote(path: str, topology: str, entry: dict, value: float,
            maximize: bool = True, provenance: Optional[dict] = None,
            hysteresis: float = 0.02) -> bool:
    """Write ``entry`` as ``topology``'s promoted defaults when
    ``value`` beats the currently-promoted value by more than
    ``hysteresis`` (sign-aware) — noise can't flip defaults back and
    forth, and other topologies' rows are never touched.  Returns
    whether the file was written."""
    doc = load_defaults(path)
    current = doc["topologies"].get(topology) or {}
    prev = (current.get("promoted_from") or {})
    prev_val = prev.get("value")
    if prev_val is not None:
        margin = 1.0 + hysteresis
        beats = value > prev_val * margin if maximize \
            else value < prev_val / margin
        if not beats:
            return False
    row = dict(entry)
    row["promoted_from"] = dict(provenance or {}, value=value,
                                maximize=maximize,
                                ts=(provenance or {}).get("ts")
                                or time.time())
    doc["topologies"][topology] = row
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return True


def apply_env_defaults(entry: dict, environ=None) -> dict:
    """``os.environ.setdefault`` every knob in the entry's ``env`` dict
    (explicit env always wins over a promoted default); returns the
    knobs actually applied."""
    environ = os.environ if environ is None else environ
    applied = {}
    for name, value in (entry.get("env") or {}).items():
        if name not in environ:
            environ[name] = str(value)
            applied[name] = value
    return applied
