"""Seed-import of the banked measurement history into the journal.

The repo carries five BENCH_r0*.json round records and the append-only
BENCH_LOG.jsonl of every successful chip measurement.  Importing them
as trials (``python -m mxnet_tpu.autotune --import-history``) starts
the cost model warm — the 2332-imgs/sec v5e rows teach it the b256
bf16 region before the first new chip minute is spent — and puts the
r02–r05 tunnel-hang rounds on the record as failed trials (config
unknown, so they inform nothing but the history is one file).

Idempotent per source file: a source already present in the journal is
skipped, so re-running --import-history never duplicates rows.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Dict

from .journal import Journal, Trial

_REMAP = (("batch", "BENCH_BATCH"),
          ("dtype", "BENCH_DTYPE"),
          ("opt", "BENCH_OPT"),
          ("steps_per_call", "BENCH_STEPS_PER_CALL"),
          ("stem", "BENCH_STEM"),
          ("layout", "BENCH_LAYOUT"))


def _remat_str(v) -> str:
    if v in (False, None, "0", "", "False", "false", 0):
        return "0"
    if v in (True, "1", "full", "True", "true", 1):
        return "1"
    return str(v)


def _config_from_log_row(d: dict) -> dict:
    cfg = {}
    for field, knob in _REMAP:
        if field in d and d[field] is not None:
            cfg[knob] = d[field]
    cfg["BENCH_REMAT"] = _remat_str(d.get("remat"))
    return cfg


def _float_ts(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def import_history(journal: Journal, root: str) -> Dict[str, int]:
    """Import BENCH_LOG.jsonl + BENCH_r0*.json under ``root`` into
    ``journal``; returns {source: rows imported} (0 = already there)."""
    done = journal.sources()
    counts: Dict[str, int] = {}
    num = journal.next_num()

    src = "BENCH_LOG.jsonl"
    log_path = os.path.join(root, src)
    counts[src] = 0
    if src not in done and os.path.exists(log_path):
        with open(log_path) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(d, dict) or "metric" not in d:
                    continue
                ok = bool(d.get("value"))
                journal.append(Trial(
                    num=num, target="bench",
                    config=_config_from_log_row(d),
                    status="ok" if ok else "error",
                    objective=float(d["value"]) if ok else None,
                    metrics={k: d.get(k) for k in
                             ("metric", "mfu", "step_ms", "device",
                              "data_mode", "tag", "wire_bytes_per_step",
                              "overlap_pct")
                             if d.get(k) is not None},
                    error=None if ok else str(d.get("error", ""))[:400],
                    source=src, ts=_float_ts(d.get("ts"))))
                num += 1
                counts[src] += 1

    for path in sorted(glob.glob(os.path.join(root, "BENCH_r0*.json"))):
        src = os.path.basename(path)
        counts.setdefault(src, 0)
        if src in done:
            continue
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(d, dict):
            continue
        tail = str(d.get("tail", ""))
        hang = ("timed out" in tail or "tunnel hang" in tail
                or "stalled" in tail)
        # config unknown for the round records — an EMPTY config marks
        # it (searcher dedup skips unknown-config trials; they must not
        # shadow the registry-default config)
        journal.append(Trial(
            num=num, target="bench", config={},
            status=("timeout" if hang else
                    "crash" if d.get("rc") else "ok"),
            objective=None,
            metrics={"round": d.get("n"), "rc": d.get("rc")},
            error=tail.strip()[-400:] or None,
            source=src))
        num += 1
        counts[src] += 1
    return counts
