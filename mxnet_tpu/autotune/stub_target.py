"""Deterministic stub measure backend — the tier-1 stand-in for a chip.

Reads two REAL registry knobs from the environment and prints one JSON
measurement line whose value is an analytic bowl with a known best
(window=8, chunk=4) — so searcher convergence, journaling, resume,
timeout handling and per-topology promotion are all testable on CPU in
milliseconds, before a chip session ever runs.

Run by PATH (not ``-m``): stdlib only, no mxnet_tpu/jax import — a
6-trial CI sweep must cost seconds.  Test hooks (MXT_ prefix: harness
controls, not framework knobs):

* ``MXT_AUTOTUNE_STUB_SLEEP_S`` — hold this long before replying (the
  deliberately-hanging target for executor timeout/kill tests);
* ``MXT_AUTOTUNE_STUB_CRASH=1`` — exit nonzero before printing;
* ``MXT_AUTOTUNE_STUB_DEVICE`` — device field override (topology tests).
"""
import json
import math
import os
import sys
import time

KNOB_WINDOW = "MXNET_KVSTORE_WINDOW"
KNOB_CHUNK = "MXNET_KVSTORE_FUSED_CHUNK"

BEST = {KNOB_WINDOW: 8, KNOB_CHUNK: 4}


def objective(window: int, chunk: int) -> float:
    """Analytic bowl, maximized exactly at the BEST config."""
    w = math.log2(max(1, window))
    c = math.log2(max(1, chunk))
    return round(100.0 - 6.0 * (w - 3.0) ** 2 - 4.0 * (c - 2.0) ** 2, 4)


def main() -> int:
    sleep_s = float(os.environ.get("MXT_AUTOTUNE_STUB_SLEEP_S", "0"))
    if sleep_s > 0:
        time.sleep(sleep_s)
    if os.environ.get("MXT_AUTOTUNE_STUB_CRASH") == "1":
        print("stub: deliberate crash before the JSON line",
              file=sys.stderr)
        return 7
    window = int(os.environ.get(KNOB_WINDOW, "8"))
    chunk = int(os.environ.get(KNOB_CHUNK, "8"))
    print(json.dumps({
        "metric": "stub_throughput",
        "value": objective(window, chunk),
        "unit": "units/sec",
        "device": os.environ.get("MXT_AUTOTUNE_STUB_DEVICE", "cpu-stub"),
        KNOB_WINDOW: window,
        KNOB_CHUNK: chunk,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
