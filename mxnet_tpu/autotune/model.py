"""The fit-on-the-fly cost model: ridge regression over knob features.

Deliberately tiny (closed-form normal equations over the
``SearchSpace.encode`` features — one-hot choices, normalized ranges):
with tens of trials per sweep, a learned-GNN TpuGraphs-style model has
nothing to chew on, but a linear model over one-hot knob indicators
already captures "window 8 beats window 1" and "2bit helps at batch
512" — enough to steer scarce chip minutes toward the frontier instead
of the grid (the pruning role the TVM loop gives its XGBoost ranker,
arXiv:1802.04799 §5).  The searcher treats it as advisory: epsilon
exploration keeps measuring off-model configs, and every measurement
refits.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .journal import Trial
from .space import SearchSpace


class CostModel:
    """Ridge regressor mapping encoded configs -> objective."""

    def __init__(self, space: SearchSpace, l2: float = 1e-2):
        self.space = space
        self.l2 = float(l2)
        self._w: Optional[np.ndarray] = None

    @property
    def fitted(self) -> bool:
        return self._w is not None

    def fit(self, trials: List[Trial]) -> bool:
        """Fit on the ok trials; False when there is not enough signal
        (fewer than 2 distinct measured configs)."""
        rows, ys = [], []
        for t in trials:
            if not t.ok:
                continue
            rows.append(self.space.encode(t.config))
            ys.append(float(t.objective))
        if len(rows) < 2:
            self._w = None
            return False
        x = np.asarray(rows, dtype=np.float64)
        x = np.concatenate([x, np.ones((x.shape[0], 1))], axis=1)  # bias
        y = np.asarray(ys, dtype=np.float64)
        # normal equations with an l2 floor: always solvable, even for
        # the rank-deficient few-trials start
        a = x.T @ x + self.l2 * np.eye(x.shape[1])
        self._w = np.linalg.solve(a, x.T @ y)
        return True

    def predict(self, configs: List[dict]) -> np.ndarray:
        if self._w is None:
            raise RuntimeError("CostModel.predict before a successful fit")
        x = np.asarray([self.space.encode(c) for c in configs],
                       dtype=np.float64)
        x = np.concatenate([x, np.ones((x.shape[0], 1))], axis=1)
        return x @ self._w
