"""Proposal strategies: random / grid baselines + the epsilon-greedy
model searcher.

Determinism contract (pinned in tests/test_autotune.py): the next
proposal is a pure function of (journal contents, seed).  Every
``propose`` call seeds a fresh RNG from ``(seed, len(trials))`` — so a
resumed sweep, a re-run after a kill, or an identical journal on
another machine all propose the SAME next config, regardless of how
many proposals this process already made.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..base import MXNetError
from .journal import Trial
from .model import CostModel
from .space import SearchSpace


def _rng_for(seed: int, n_trials: int) -> np.random.RandomState:
    # mix, then clamp into RandomState's 32-bit seed domain
    return np.random.RandomState((seed * 1000003 + n_trials) % (2 ** 32))


class Searcher:
    def __init__(self, space: SearchSpace, maximize: bool = True,
                 seed: int = 0):
        self.space = space
        self.maximize = maximize
        self.seed = int(seed)

    # -- shared helpers -----------------------------------------------------
    def _measured(self, trials: List[Trial]) -> set:
        # Only trials THIS harness measured block re-proposal.  Imported
        # history (source = a file name) warms the cost model but never
        # vetoes a config: the banked rows may be from another device/
        # pre-TCP_NODELAY era, and re-measuring the historical best is
        # often exactly the point (the roadmap's re-baseline).  config
        # {} marks an imported round whose settings are unknown — it
        # must not shadow the registry-default config either.
        return {self.space.canonical(t.config) for t in trials
                if t.config and t.source == "measured"}

    def _random_unmeasured(self, rng, measured, tries: int = 128) -> dict:
        cand = self.space.sample(rng)
        for _ in range(tries):
            if self.space.canonical(cand) not in measured:
                return cand
            cand = self.space.sample(rng)
        return cand      # space exhausted (tiny all-choice spaces): re-measure

    def _best(self, trials: List[Trial]) -> Optional[Trial]:
        ok = [t for t in trials if t.ok]
        if not ok:
            return None
        key = (lambda t: t.objective) if self.maximize \
            else (lambda t: -t.objective)
        return max(ok, key=key)

    def propose(self, trials: List[Trial]) -> dict:
        raise NotImplementedError


class RandomSearcher(Searcher):
    def propose(self, trials: List[Trial]) -> dict:
        rng = _rng_for(self.seed, len(trials))
        return self._random_unmeasured(rng, self._measured(trials))


class GridSearcher(Searcher):
    """Deterministic enumeration of the per-axis grids; the journal is
    the cursor — the first grid point not yet measured is next."""

    def __init__(self, space, maximize=True, seed=0, grid_points: int = 5):
        super().__init__(space, maximize=maximize, seed=seed)
        self.grid_points = int(grid_points)

    def propose(self, trials: List[Trial]) -> dict:
        measured = self._measured(trials)
        for config in self.space.grid(self.grid_points):
            if self.space.canonical(config) not in measured:
                return config
        # grid exhausted: keep exploring off-grid points
        return self._random_unmeasured(_rng_for(self.seed, len(trials)),
                                       measured)


class ModelSearcher(Searcher):
    """Epsilon-greedy over the ridge cost model: with probability
    epsilon explore a random unmeasured config; otherwise fit on the
    journal and take the best-scored unmeasured candidate from a pool
    of random samples + neighbors of the measured best + the registry
    defaults.  Scores carry a count-based NOVELTY bonus — an axis value
    no ok trial has touched adds (objective spread)/len(axes) toward
    the optimization direction — because a linear model scores
    never-observed one-hot columns at zero and would otherwise starve
    whole axes of measurement (the classic cold-start pathology; the
    TVM loop solves it with epsilon + diversity, arXiv:1802.04799 §5.3)."""

    def __init__(self, space, maximize=True, seed=0, epsilon: float = 0.25,
                 candidates: int = 64, novelty_weight: float = 1.0):
        super().__init__(space, maximize=maximize, seed=seed)
        self.epsilon = float(epsilon)
        self.candidates = int(candidates)
        self.novelty_weight = float(novelty_weight)

    def _novelty(self, config: dict, trials: List[Trial]) -> float:
        """Fraction of this config's axis values never measured ok."""
        seen = {n: set() for n in self.space.axes}
        for t in trials:
            if not t.ok or not t.config:
                continue
            for n, a in self.space.axes.items():
                seen[n].add(a.coerce(t.config.get(n, a.default)))
        fresh = sum(
            1 for n, a in self.space.axes.items()
            if a.coerce(config.get(n, a.default)) not in seen[n])
        return fresh / len(self.space.axes)

    def propose(self, trials: List[Trial]) -> dict:
        rng = _rng_for(self.seed, len(trials))
        measured = self._measured(trials)
        explore = rng.uniform() < self.epsilon
        model = CostModel(self.space)
        if explore or not model.fit(trials):
            return self._random_unmeasured(rng, measured)
        pool = [self.space.sample(rng) for _ in range(self.candidates)]
        pool.append(self.space.default_config())
        best = self._best(trials)
        if best is not None:
            pool.extend(self.space.neighbors(best.config))
        seen = set()
        fresh = []
        for c in pool:
            key = self.space.canonical(c)
            if key in measured or key in seen:
                continue
            seen.add(key)
            fresh.append(c)
        if not fresh:
            return self._random_unmeasured(rng, measured)
        scores = model.predict(fresh)
        ys = [t.objective for t in trials if t.ok]
        spread = (max(ys) - min(ys)) if len(ys) >= 2 else 1.0
        spread = spread or 1.0
        sign = 1.0 if self.maximize else -1.0
        bonus = self.novelty_weight * spread
        scored = [s + sign * bonus * self._novelty(c, trials)
                  for s, c in zip(scores, fresh)]
        i = int(np.argmax(scored) if self.maximize
                else np.argmin(scored))
        return fresh[i]


def make_searcher(strategy: str, space: SearchSpace, maximize: bool,
                  seed: int, epsilon: float = 0.25,
                  candidates: int = 64) -> Searcher:
    if strategy == "random":
        return RandomSearcher(space, maximize=maximize, seed=seed)
    if strategy == "grid":
        return GridSearcher(space, maximize=maximize, seed=seed)
    if strategy == "model":
        return ModelSearcher(space, maximize=maximize, seed=seed,
                             epsilon=epsilon, candidates=candidates)
    raise MXNetError("autotune: unknown strategy %r (model|random|grid)"
                     % strategy)
