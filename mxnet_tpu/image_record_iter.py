"""ImageRecordIter: native-pipeline image-record iterator.

TPU-native equivalent of the reference's C++ ImageRecordIter
(src/io/iter_image_recordio_2.cc, registered in src/io/io.cc:337): sharded
record reads, OMP-parallel JPEG decode+resize in C++
(mxnet_tpu/native/io_native.cc), vectorized augment (mirror/mean/std) in
numpy, and a double-buffered background prefetch thread standing in for
dmlc::ThreadedIter (src/io/iter_prefetcher.h).  Falls back to the PIL
decode path when the native library can't build.
"""
from __future__ import annotations

import logging
import os
import queue
import threading

import numpy as np

from .base import MXNetError, env
from .io import DataBatch, DataDesc, DataIter, _ProducerError
from .ndarray.ndarray import array as nd_array
from . import recordio
from . import native


class ImageRecordIter(DataIter):
    """reference params mirror src/io/image_rec_parser params +
    augmenter params (image_aug_default.cc)."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_width=1, shuffle=False, mean_r=0.0, mean_g=0.0,
                 mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0,
                 rand_mirror=False, rand_crop=False, resize=-1,
                 part_index=0, num_parts=1, round_batch=True,
                 preprocess_threads=None, prefetch_buffer=2, seed=0,
                 data_name='data', label_name='softmax_label',
                 device_prefetch=False, device=None, **kwargs):
        super().__init__(batch_size)
        # device_prefetch: keep ONE batch in flight to the device —
        # next() returns the already-transferring batch t and immediately
        # starts batch t+1's async jax.device_put, so the host→device
        # copy overlaps the consumer's compute (the transfer leg of the
        # reference's ThreadedIter overlap; the decode/augment leg is the
        # _producer thread below).  Feeds the multi-step driver
        # (Module.run_steps) without any host work on the hot path.
        self._device_prefetch = device_prefetch
        self._device = device
        self._dev_next = None
        self._dev_err = None
        if not os.path.exists(path_imgrec):
            raise MXNetError(f"record file not found: {path_imgrec}")
        self.path = path_imgrec
        self.data_shape = tuple(data_shape)
        assert len(self.data_shape) == 3, "data_shape must be (C, H, W)"
        self.batch_size = batch_size
        self.label_width = label_width
        self.shuffle = shuffle
        self.rand_mirror = rand_mirror
        self.rand_crop = rand_crop
        self.resize = resize
        self.round_batch = round_batch
        self._rng = np.random.RandomState(seed)
        self.mean = np.array([mean_r, mean_g, mean_b],
                             np.float32).reshape(3, 1, 1)
        self.std = np.array([std_r, std_g, std_b],
                            np.float32).reshape(3, 1, 1)
        self.nthreads = preprocess_threads or \
            env("MXNET_CPU_WORKER_NTHREADS", os.cpu_count() or 4)

        self._native = native.available()
        if self._native:
            offsets = native.index_rec_file(path_imgrec)
        else:
            logging.warning("ImageRecordIter: native IO lib unavailable, "
                            "using PIL fallback (slower)")
            offsets = self._py_index()
        # shard for this worker (reference: dmlc InputSplit partitioning)
        if num_parts > 1:
            n = len(offsets)
            c = n // num_parts
            offsets = offsets[part_index * c:(part_index + 1) * c]
        self._offsets = np.asarray(offsets, dtype=np.int64)
        self._order = np.arange(len(self._offsets))

        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc(
            label_name, (batch_size, label_width) if label_width > 1
            else (batch_size,))]

        self._prefetch_n = prefetch_buffer
        self._queue = None
        self._worker = None
        self._stop = threading.Event()
        self.reset()

    def _py_index(self):
        offsets = []
        r = recordio.MXRecordIO(self.path, 'r')
        while True:
            pos = r.tell()
            if r.read() is None:
                break
            offsets.append(pos)
        r.close()
        return np.asarray(offsets, dtype=np.int64)

    # -- pipeline ----------------------------------------------------------
    def _load_batch(self, idxs):
        offs = self._offsets[idxs]
        if self._native:
            raws = native.read_records(self.path, offs)
        else:
            r = recordio.MXRecordIO(self.path, 'r')
            raws = []
            for o in offs:
                r.seek(int(o))
                raws.append(r.read())
            r.close()
        labels = np.zeros((len(raws), self.label_width), np.float32)
        jpegs = []
        for i, raw in enumerate(raws):
            header, img = recordio.unpack(raw)
            lab = np.atleast_1d(np.asarray(header.label, np.float32))
            labels[i, :min(self.label_width, lab.size)] = \
                lab[:self.label_width]
            jpegs.append(img)
        c, h, w = self.data_shape
        # decode size must cover the crop; with resize set, decode at
        # (>=resize, aspect not preserved — a deliberate simplification of
        # the reference's shorter-edge resize) but never below (h, w)
        dec_h = max(h, self.resize) if self.resize > 0 else h
        dec_w = max(w, self.resize) if self.resize > 0 else w
        if self._native and hasattr(native.get_lib(),
                                    "jpeg_decode_augment_batch"):
            # fused native path: decode+crop+mirror+normalize+NCHW in one
            # OMP pass (io_native.cc jpeg_decode_augment_batch); augmenter
            # randomness drawn here so semantics match the split path
            nimg = len(jpegs)
            # rng is consumed only when a crop actually happens — the same
            # condition as the split path, so seeds stay reproducible
            # across both
            if (dec_h != h or dec_w != w) and self.rand_crop:
                y0 = self._rng.randint(0, dec_h - h + 1, nimg)
                x0 = self._rng.randint(0, dec_w - w + 1, nimg)
            else:
                y0 = np.full(nimg, (dec_h - h) // 2, np.int32)
                x0 = np.full(nimg, (dec_w - w) // 2, np.int32)
            flips = (self._rng.rand(nimg) < 0.5 if self.rand_mirror
                     else np.zeros(nimg, bool))
            arr, fails = native.decode_augment_batch(
                jpegs, dec_h, dec_w, h, w, y0, x0, flips,
                self.mean.ravel()[:c], self.std.ravel()[:c], c,
                self.nthreads)
            if fails:
                logging.debug("%d corrupt images zero-filled", fails)
            labels = labels[:, 0] if self.label_width == 1 else labels
            return arr, labels
        if self._native:
            arr, fails = native.decode_jpeg_batch(
                jpegs, dec_h, dec_w, c, self.nthreads)
            if fails:
                logging.debug("%d corrupt images zero-filled", fails)
        else:
            from .image import imdecode
            outs = []
            for b in jpegs:
                im = np.asarray(imdecode(b, 1 if c == 3 else 0)
                                .asnumpy(), np.uint8)
                from PIL import Image
                im = np.asarray(Image.fromarray(
                    im if c == 3 else im[:, :, 0]).resize(
                        (dec_w, dec_h), Image.BILINEAR), np.uint8)
                if c == 1:
                    im = im[:, :, None]
                outs.append(im)
            arr = np.stack(outs)
        # random / center crop to (h, w) — offsets drawn vectorized, the
        # SAME rng consumption as the fused native path, so a given seed
        # crops identically whether or not the native lib is present
        if arr.shape[1] != h or arr.shape[2] != w:
            H, W = arr.shape[1], arr.shape[2]
            nimg = arr.shape[0]
            if self.rand_crop:
                y0s = self._rng.randint(0, H - h + 1, nimg)
                x0s = self._rng.randint(0, W - w + 1, nimg)
            else:
                y0s = np.full(nimg, (H - h) // 2, np.int64)
                x0s = np.full(nimg, (W - w) // 2, np.int64)
            out = np.empty((nimg, h, w, c), arr.dtype)
            for i in range(nimg):
                out[i] = arr[i, y0s[i]:y0s[i] + h, x0s[i]:x0s[i] + w]
            arr = out
        # NHWC uint8 -> NCHW float32, mirror, normalize (vectorized)
        arr = arr.transpose(0, 3, 1, 2).astype(np.float32)
        if self.rand_mirror:
            flip = self._rng.rand(arr.shape[0]) < 0.5
            arr[flip] = arr[flip, :, :, ::-1]
        if self.mean.any():
            arr -= self.mean
        if (self.std != 1.0).any():
            arr /= self.std
        labels = labels[:, 0] if self.label_width == 1 else labels
        return arr, labels

    def _producer(self, order, out_queue, stop):
        # queue/stop passed by value: a worker outliving reset() keeps
        # talking to ITS epoch's queue, never the replacement's
        try:
            n = len(order)
            for start in range(0, n - self.batch_size + 1,
                               self.batch_size):
                if stop.is_set():
                    return
                idxs = order[start:start + self.batch_size]
                out_queue.put(self._load_batch(idxs))
            rem = n % self.batch_size
            if rem and self.round_batch:
                # wrap around to fill the final batch (reference:
                # round_batch pads from the epoch start); datasets smaller
                # than batch_size tile cyclically
                idxs = np.concatenate([order[n - rem:],
                                       order[np.arange(
                                           self.batch_size - rem) % n]])
                batch = self._load_batch(idxs)
                out_queue.put(batch + (self.batch_size - rem,))
        except BaseException as e:  # noqa: BLE001 — crossing a thread
            # surface the failure on the CONSUMER side: without this, a
            # corrupt/mis-shaped record would look like a (possibly empty)
            # end of epoch — silent truncation, and a permanent hang for
            # any caller double-buffering off this iterator
            out_queue.put(_ProducerError(e))
        finally:
            out_queue.put(None)

    def reset(self):
        self._stop.set()
        if self._worker is not None:
            # drain so the producer can observe stop and exit
            try:
                while self._queue.get_nowait() is not None:
                    pass
            except queue.Empty:
                pass
            self._worker.join(timeout=5)
            if self._worker.is_alive():
                # a wedged producer can't corrupt the NEW epoch (it holds
                # the old queue/stop objects), but it is a leaked thread
                # pinning file handles — say so instead of masking it
                logging.warning(
                    "ImageRecordIter.reset: previous prefetch worker did "
                    "not stop within 5s (stuck in native decode/IO?); "
                    "leaking the daemon thread")
        self._stop = threading.Event()
        self._done = False
        self._dev_next = None   # drop any in-flight device batch
        self._dev_err = None    # ...and any parked prefetch failure
        order = self._order.copy()
        if self.shuffle:
            self._rng.shuffle(order)
        self._queue = queue.Queue(maxsize=self._prefetch_n)
        self._worker = threading.Thread(
            target=self._producer, args=(order, self._queue, self._stop),
            daemon=True)
        self._worker.start()

    def next_raw(self):
        """Next batch as HOST numpy arrays (data, label, pad) — no NDArray
        wrap, no device transfer.  For callers that manage placement
        themselves (bench.py does ONE uint8 device_put per batch; wrapping
        through next() would eagerly device_put and cost extra
        host<->device crossings on a remote-attached chip)."""
        if self._done:
            raise StopIteration
        while True:
            try:
                item = self._queue.get(timeout=1.0)
                break
            except queue.Empty:
                # the producer posts a sentinel even on failure (its
                # finally clause) — an empty queue with a DEAD worker
                # means the thread was killed outright; hanging here
                # forever would silently wedge training
                if self._worker is not None and not self._worker.is_alive():
                    self._done = True
                    raise MXNetError(
                        "ImageRecordIter: prefetch worker died without "
                        "reporting a result — cannot continue the epoch")
        if item is None:
            self._done = True
            raise StopIteration
        if isinstance(item, _ProducerError):
            self._done = True
            raise MXNetError(
                "ImageRecordIter pipeline failed in the prefetch thread: "
                "%r" % (item.exc,)) from item.exc
        if len(item) == 3:
            data, label, pad = item
        else:
            data, label = item
            pad = 0
        return data, label, pad

    def _device_batch(self):
        """Next batch with its async device transfer already started."""
        import jax
        data, label, pad = self.next_raw()
        from .ndarray import NDArray
        return DataBatch(
            [NDArray(jax.device_put(data, self._device))],
            [NDArray(jax.device_put(label, self._device))], pad=pad,
            provide_data=self.provide_data,
            provide_label=self.provide_label)

    def next(self):
        if self._device_prefetch:
            if self._dev_err is not None:
                err, self._dev_err = self._dev_err, None
                raise err
            cur = self._dev_next
            if cur is None:
                cur = self._device_batch()   # first call of the epoch
            try:
                # start batch t+1's transfer before handing out batch t:
                # the copy overlaps the consumer's compute
                self._dev_next = self._device_batch()
            except StopIteration:
                self._dev_next = None
            except Exception as e:  # noqa: BLE001 — t+1's pipeline died,
                # but batch t in hand is GOOD: deliver it, raise on the
                # NEXT call (dropping cur would silently consume a batch
                # from the record stream without ever training on it)
                self._dev_next = None
                self._dev_err = e
            return cur
        data, label, pad = self.next_raw()
        return DataBatch([nd_array(data)], [nd_array(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ImageRecordUInt8Iter(ImageRecordIter):
    """Raw pre-decoded uint8 records: no JPEG decode at training time.

    Reference: ImageRecordUInt8Iter (src/io/io.cc:337-758) — the input-
    pipeline fast path when the host CPU cannot decode fast enough to feed
    the accelerator.  Records carry fixed-shape HWC uint8 payloads (pack
    with ``tools/im2rec.py --pack-raw S``); iteration is pure byte movement
    (crop + mirror + NCHW in native code, io_native.cc crop_flip_u8_batch).
    Output batches are uint8 NCHW — normalization belongs ON DEVICE, where
    it fuses into the training step (e.g. ResNet's bn_data input
    BatchNorm); mean/std parameters are therefore rejected here, exactly
    like the reference's uint8 iterator which ignores them.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 stored_shape=None, output_layout="NCHW", **kwargs):
        identity = {"mean_r": 0.0, "mean_g": 0.0, "mean_b": 0.0,
                    "std_r": 1.0, "std_g": 1.0, "std_b": 1.0}
        for k, ident in identity.items():
            v = kwargs.pop(k, None)
            if v is not None and float(v) != ident:
                raise MXNetError(
                    "ImageRecordUInt8Iter outputs raw uint8; apply "
                    "mean/std on device (it fuses into the step)")
        if output_layout not in ("NCHW", "NHWC"):
            raise MXNetError(
                f"output_layout must be NCHW or NHWC, got {output_layout}")
        # NHWC is the host FAST path: an unflipped row is one memcpy
        # (~10x the NCHW gather on one core) and the HWC->CHW transpose
        # moves to the device where it fuses into the uint8->bf16 cast
        self._output_layout = output_layout
        self._stored_shape = tuple(stored_shape) if stored_shape else None
        super().__init__(path_imgrec, data_shape, batch_size, **kwargs)
        if output_layout == "NHWC":
            c, h, w = self.data_shape
            self.provide_data = [DataDesc(self.provide_data[0].name,
                                          (batch_size, h, w, c),
                                          dtype=np.uint8, layout="NHWC")]

    def _infer_stored_shape(self, payload_len):
        c = self.data_shape[0]
        if payload_len % c:
            raise MXNetError(
                f"raw record payload {payload_len} not divisible by "
                f"channels {c}")
        side = int(round((payload_len // c) ** 0.5))
        if side * side * c != payload_len:
            raise MXNetError(
                f"raw record payload {payload_len} is not square; pass "
                f"stored_shape=(H, W)")
        return (side, side)

    def _load_batch(self, idxs):
        offs = self._offsets[idxs]
        if self._native:
            raws = native.read_records(self.path, offs)
        else:
            r = recordio.MXRecordIO(self.path, 'r')
            raws = []
            for o in offs:
                r.seek(int(o))
                raws.append(r.read())
            r.close()
        labels = np.zeros((len(raws), self.label_width), np.float32)
        payloads = []
        for i, raw in enumerate(raws):
            header, img = recordio.unpack(raw)
            lab = np.atleast_1d(np.asarray(header.label, np.float32))
            labels[i, :min(self.label_width, lab.size)] = \
                lab[:self.label_width]
            payloads.append(img)
        c, h, w = self.data_shape
        if self._stored_shape is None:
            self._stored_shape = self._infer_stored_shape(len(payloads[0]))
        dh, dw = self._stored_shape
        nimg = len(payloads)
        if (dh != h or dw != w) and self.rand_crop:
            y0 = self._rng.randint(0, dh - h + 1, nimg)
            x0 = self._rng.randint(0, dw - w + 1, nimg)
        else:
            y0 = np.full(nimg, (dh - h) // 2, np.int32)
            x0 = np.full(nimg, (dw - w) // 2, np.int32)
        flips = (self._rng.rand(nimg) < 0.5 if self.rand_mirror
                 else np.zeros(nimg, bool))
        nhwc = self._output_layout == "NHWC"
        # feature-test the EXACT symbol: a stale prebuilt .so may carry
        # crop_flip_u8_batch but not the newer nhwc variant
        want_sym = "crop_flip_u8_nhwc_batch" if nhwc \
            else "crop_flip_u8_batch"
        if self._native and hasattr(native.get_lib(), want_sym):
            fn = native.crop_flip_u8_nhwc_batch if nhwc \
                else native.crop_flip_u8_batch
            arr = fn(payloads, dh, dw, h, w, y0, x0, flips, c,
                     self.nthreads)
        else:  # pure-numpy fallback, same semantics
            arr = np.empty((nimg, h, w, c) if nhwc else (nimg, c, h, w),
                           np.uint8)
            for i, p in enumerate(payloads):
                im = np.asarray(p, dtype=np.uint8).reshape(dh, dw, c) \
                    if isinstance(p, np.ndarray) \
                    else np.frombuffer(p, np.uint8).reshape(dh, dw, c)
                crop = im[y0[i]:y0[i] + h, x0[i]:x0[i] + w]
                if flips[i]:
                    crop = crop[:, ::-1]
                arr[i] = crop if nhwc else crop.transpose(2, 0, 1)
        labels = labels[:, 0] if self.label_width == 1 else labels
        return arr, labels
