"""Profiler: chrome://tracing JSON + XLA (xplane) trace capture.

TPU-native re-design of the reference profiler (src/engine/profiler.h:79
OprExecStat collection inside the engine; python/mxnet/profiler.py:27-55
set_config/set_state/dump_profile).  Two layers:

* **host events** — the dispatch layer (eager `_invoke`, Executor
  forward/backward, fused Module steps) records {name, start µs, dur µs}
  pairs exactly like the reference's per-opr stats, dumped in
  chrome://tracing format so the same tooling opens both.
* **device truth** — `start()/stop()` also drive `jax.profiler`
  (``MXNET_PROFILER_XLA_LOGDIR``), capturing the XLA/TPU xplane trace;
  per-op names survive into HLO metadata.

Env parity: ``MXNET_PROFILER_AUTOSTART=1`` begins profiling at import
(reference: src/engine/profiler.cc autostart).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

from .base import MXNetError, env
from . import tracing
from . import health as _health

PROFILER_STATE_STOP = 0
PROFILER_STATE_RUN = 1

_MODE_SYMBOLIC = "symbolic"
_MODE_ALL = "all"


class _Profiler:
    def __init__(self):
        self.state = PROFILER_STATE_STOP
        # reference env parity: MXNET_PROFILER_MODE=all widens capture
        # beyond dispatch events; any other value (incl. the reference
        # spelling "symbolic_only") is the symbolic default.
        # profiler_set_config overrides at runtime.
        self.mode = _MODE_ALL \
            if env("MXNET_PROFILER_MODE", "symbolic_only") == _MODE_ALL \
            else _MODE_SYMBOLIC
        self.filename = "profile.json"
        self.continuous_dump = False
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._xla_logdir: Optional[str] = None
        self._xla_running = False

    # -- event capture -----------------------------------------------------
    def record(self, name, start_us, dur_us, category="operator",
               tid=None):
        if self.state != PROFILER_STATE_RUN:
            return
        with self._lock:
            self._events.append({
                "name": name, "cat": category, "ph": "X",
                "ts": start_us, "dur": dur_us,
                "pid": os.getpid(),
                "tid": tid if tid is not None else
                threading.get_ident() % 100000,
            })

    def scope(self, name, category="operator"):
        return _Scope(self, name, category)

    # -- lifecycle ---------------------------------------------------------
    def set_state(self, state):
        if state == PROFILER_STATE_RUN and \
                self.state != PROFILER_STATE_RUN:
            self._maybe_start_xla()
        if state == PROFILER_STATE_STOP and \
                self.state == PROFILER_STATE_RUN:
            self._maybe_stop_xla()
            if self.continuous_dump:
                self.state = state
                self.dump()
        self.state = state

    def _maybe_start_xla(self):
        logdir = self._xla_logdir or env("MXNET_PROFILER_XLA_LOGDIR", None)
        if logdir:
            import jax
            jax.profiler.start_trace(logdir)
            self._xla_running = True

    def _maybe_stop_xla(self):
        if self._xla_running:
            import jax
            jax.profiler.stop_trace()
            self._xla_running = False

    def dump(self, finished=True):
        with self._lock:
            events = list(self._events)
            if finished:
                self._events = []
        with open(self.filename, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)


class _Scope:
    __slots__ = ("_p", "_name", "_cat", "_t0")

    def __init__(self, p, name, cat):
        self._p = p
        self._name = name
        self._cat = cat

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *a):
        if self._p.state == PROFILER_STATE_RUN:
            t1 = time.perf_counter_ns()
            self._p.record(self._name, self._t0 // 1000,
                           (t1 - self._t0) // 1000, self._cat)


_profiler = _Profiler()


def profiler_set_config(mode="symbolic", filename="profile.json",
                        continuous_dump=False, xla_logdir=None, **kwargs):
    """reference: profiler.py:27 profiler_set_config / MXSetProfilerConfig.

    ``xla_logdir``: directory for the device (xplane) capture that
    start/stop also drives — the public form of the
    ``MXNET_PROFILER_XLA_LOGDIR`` env var.  None leaves the current
    setting untouched; the empty string "" CLEARS it (device capture
    off).  Merge both outputs with tools/trace_merge.py.
    """
    if mode not in (_MODE_SYMBOLIC, _MODE_ALL):
        raise MXNetError(f"invalid profiler mode {mode!r}")
    if kwargs:
        import warnings
        warnings.warn("profiler_set_config: ignoring unknown options %r"
                      % sorted(kwargs), stacklevel=2)
    _profiler.mode = mode
    _profiler.filename = filename
    _profiler.continuous_dump = continuous_dump
    if xla_logdir is not None:
        _profiler._xla_logdir = xla_logdir or None  # "" clears


set_config = profiler_set_config


def profiler_set_state(state="stop"):
    """reference: profiler.py:40 / MXSetProfilerState."""
    s = {"stop": PROFILER_STATE_STOP, "run": PROFILER_STATE_RUN}
    if state not in s:
        raise MXNetError(f"invalid profiler state {state!r}")
    _profiler.set_state(s[state])


set_state = profiler_set_state


def dump_profile():
    """reference: profiler.py:52 dump_profile / MXDumpProfile."""
    _profiler.dump()


dump = dump_profile


def is_running():
    return _profiler.state == PROFILER_STATE_RUN


def record_event(name, start_us, dur_us, category="operator"):
    _profiler.record(name, start_us, dur_us, category)


# -- span tracing (mxnet_tpu.tracing; docs/OBSERVABILITY.md) -----------------
# The profiler's cross-process face: span_begin/span_end with a
# thread-local current span, monotonic clocks, a bounded ring and the
# MXNET_TRACE master switch all live in mxnet_tpu.tracing — re-exported
# here so instrumentation sites (and the reference-shaped public
# surface) reach them as profiler.span_begin(...) without a second
# import.
span = tracing.span
span_begin = tracing.span_begin
span_end = tracing.span_end
trace_instant = tracing.instant
trace_enabled = tracing.enabled


# -- host-dispatch counters --------------------------------------------------
# One counter per dispatch KIND (fused step launch, K-step scan launch,
# host readback, eager forward, ...).  This is the test hook behind the
# multi-step driver's contract — "run_steps(k) is ONE device dispatch and
# ONE host readback" is asserted by tests/test_run_steps.py against these
# counts, so a regression that silently reintroduces per-step host
# round-trips fails loudly instead of only showing up on a chip.
_dispatch_counts: dict = {}
_dispatch_lock = threading.Lock()


def record_dispatch(kind: str):
    """Count one host-side dispatch event of ``kind`` (always on — a
    dict increment is noise next to the device round-trip it marks)."""
    with _dispatch_lock:
        _dispatch_counts[kind] = _dispatch_counts.get(kind, 0) + 1


def dispatch_counts() -> dict:
    with _dispatch_lock:
        return dict(_dispatch_counts)


def reset_dispatch_counts():
    with _dispatch_lock:
        _dispatch_counts.clear()


# -- host-sync counters ------------------------------------------------------
# One counter per host-READBACK site (ndarray.asnumpy, metric.sync,
# predict.readback, ...).  This is the test hook behind the sync-free
# training loop: "the host touches the device once per LOG INTERVAL,
# not once per batch" is asserted by tests/test_sync_free.py and the
# ci/run_ci.sh sync-count gate against these counts, so a change that
# quietly reintroduces a per-batch device->host sync fails loudly on
# CPU instead of only showing up as step-time jitter on a chip.
# Separate from the dispatch counters: a dispatch LAUNCHES device work
# asynchronously; a sync BLOCKS the host on it — only the second one
# serializes the loop.
_host_sync_counts: dict = {}
_host_sync_lock = threading.Lock()


def record_host_sync(kind: str):
    """Count one host-blocking device readback of ``kind`` (always on —
    a dict increment is noise next to the device round-trip it marks)."""
    with _host_sync_lock:
        _host_sync_counts[kind] = _host_sync_counts.get(kind, 0) + 1


def host_syncs() -> dict:
    with _host_sync_lock:
        return dict(_host_sync_counts)


def host_sync_total() -> int:
    """Total host syncs across all sites (the gate's one number)."""
    with _host_sync_lock:
        return sum(_host_sync_counts.values())


def reset_host_syncs():
    with _host_sync_lock:
        _host_sync_counts.clear()


# -- kvstore channel counters ------------------------------------------------
# One counter per transport-resilience event on the dist kvstore channel
# (retry, reconnect, replay, replay_acked, hard_fail, heartbeat,
# heartbeat_miss; the elastic layer adds roster_bump, the eviction/
# handoff family, coordinator_failover / coordinator_failover_observed
# and the coordinator_slot + failover_rebuild_s gauges — a coordinator
# succession is a first-class counter, not a log line).  Separate from
# the dispatch counters on purpose: the
# multi-step-driver tests assert dispatch_counts() by EXACT equality, and
# a channel retry must never be able to fail a dispatch-contract test.
# tests/test_faultinject.py asserts recovery paths against these.
_channel_counts: dict = {}
_channel_lock = threading.Lock()


def record_channel_event(kind: str):
    """Count one kvstore transport event of ``kind`` (always on — a dict
    increment is noise next to the socket round-trip it marks)."""
    with _channel_lock:
        _channel_counts[kind] = _channel_counts.get(kind, 0) + 1


def record_channel_count(kind: str, n: int):
    """Add ``n`` to the transport counter ``kind`` — the bulk form of
    :func:`record_channel_event` for per-row accounting (e.g.
    ``kvstore.sparse_rows``: one sparse push moves thousands of rows;
    counting them one event at a time would put a lock round-trip per
    row on the push path).  Lives in _channel_counts, NOT the byte
    counters, so row counts never pollute wire_bytes_total."""
    with _channel_lock:
        _channel_counts[kind] = _channel_counts.get(kind, 0) + int(n)


def record_channel_gauge(kind: str, value):
    """SET a transport gauge (last-value, not a count): the elastic
    roster generation is the canonical one — ``kvstore.roster_generation``
    must read as "which membership epoch am I on", where an increment
    per observer would be meaningless."""
    with _channel_lock:
        _channel_counts[kind] = value


def channel_counts() -> dict:
    with _channel_lock:
        return dict(_channel_counts)


def reset_channel_counts():
    with _channel_lock:
        _channel_counts.clear()


def fleet_route_counts() -> dict:
    """Per-replica routing counters for a serving fleet: {uri: attempts
    routed there}, stripped of the ``fleet.route:`` prefix.  The chaos
    gate asserts on a DELTA of this map — after a kill/blackhole, the
    dead replicas' counts must stop moving while the survivors' climb."""
    with _channel_lock:
        return {k[len("fleet.route:"):]: v
                for k, v in _channel_counts.items()
                if k.startswith("fleet.route:")}


# -- kvstore channel byte counters -------------------------------------------
# Bytes moved per transport DIRECTION ("sent"/"recv" for the socket wire,
# "allgather" for host collectives).  Separate from the event counters:
# events prove a recovery path RAN, bytes prove a wire optimization is
# real — the 2-bit compression acceptance asserts its >=8x push-byte
# reduction against these, and bench.py surfaces wire_bytes_per_step.
_channel_bytes: dict = {}


def record_channel_bytes(kind: str, n: int):
    """Add ``n`` bytes to the transport byte counter ``kind`` (always on
    — two dict ops are noise next to the socket write they measure)."""
    with _channel_lock:
        _channel_bytes[kind] = _channel_bytes.get(kind, 0) + int(n)


def channel_bytes() -> dict:
    with _channel_lock:
        return dict(_channel_bytes)


# The hierarchical kvstore tier's in-host mesh traffic counts under
# "ici_*" kinds (kvstore_server._send_msg byte_kind) — a separate
# counter FAMILY from the TCP wire, because the whole point of the tier
# is moving bytes from the wire onto the mesh: bench.py reports
# ici_bytes_per_step next to wire_bytes_per_step so the shift is a
# banked, regression-gateable number (docs/PERF_NOTES.md round 11).
ICI_BYTE_PREFIX = "ici_"

# Control-plane traffic (heartbeats, roster beats/leaves, codec hellos)
# counts under "control"/"control_recv" kinds — a third family next to
# the data wire and the mesh, so wire_bytes_per_step measures GRADIENTS
# only: a heartbeat cadence change must never move a banked wire-byte
# number.  Mesh-side control rides "ici_control*" and stays inside the
# ici_ family (the mesh totals already exclude the wire).
CONTROL_BYTE_PREFIX = "control"


def is_control_byte_kind(kind: str) -> bool:
    """True for control-plane byte kinds on either transport."""
    return (kind.startswith(CONTROL_BYTE_PREFIX)
            or kind.startswith(ICI_BYTE_PREFIX + CONTROL_BYTE_PREFIX))


# Same-host shared-memory lane traffic (mxnet_tpu/shmlane.py) counts
# under "shm_sent"/"shm_recv" — a fourth family next to the socket mesh
# kinds, because the lane's whole point is that these bytes never cross
# a socket: when MXNET_KVSTORE_SHM is on, follower<->leader payload
# moves from ici_* to shm_* and the socket's ici_* drops to control
# traffic (hellos, heartbeats).  bench.py banks shm_bytes_per_step so
# the shift is a regression-gateable number.
SHM_BYTE_PREFIX = "shm_"


def ici_bytes_total() -> int:
    """Total in-mesh (hierarchy-tier) bytes moved over SOCKETS so far;
    the shm lane's share counts under shm_bytes_total."""
    with _channel_lock:
        return sum(v for k, v in _channel_bytes.items()
                   if k.startswith(ICI_BYTE_PREFIX))


def ici_payload_bytes_total() -> int:
    """The mesh sockets' DATA share: ici_* minus ici_control* — with
    the shm lane active this is ≈0 (payload rides the ring), which is
    exactly what the CI shm gate pins."""
    with _channel_lock:
        return sum(v for k, v in _channel_bytes.items()
                   if k.startswith(ICI_BYTE_PREFIX)
                   and not k.startswith(ICI_BYTE_PREFIX
                                        + CONTROL_BYTE_PREFIX))


def shm_bytes_total() -> int:
    """Total same-host shared-memory lane bytes moved so far (both
    directions; zero socket syscalls behind any of them)."""
    with _channel_lock:
        return sum(v for k, v in _channel_bytes.items()
                   if k.startswith(SHM_BYTE_PREFIX))


def wire_bytes_total() -> int:
    """Total non-mesh DATA bytes (TCP wire + host collectives);
    control-plane traffic is excluded so the banked per-step number
    measures gradients, not heartbeat cadence — and the in-host
    families (ici_*, shm_*) are excluded so it measures the WIRE."""
    with _channel_lock:
        return sum(v for k, v in _channel_bytes.items()
                   if not k.startswith(ICI_BYTE_PREFIX)
                   and not k.startswith(CONTROL_BYTE_PREFIX)
                   and not k.startswith(SHM_BYTE_PREFIX))


def control_bytes_total() -> int:
    """Total wire-side control-plane bytes (heartbeats, roster beats,
    codec hellos); mesh-side control counts into ici_bytes_total."""
    with _channel_lock:
        return sum(v for k, v in _channel_bytes.items()
                   if k.startswith(CONTROL_BYTE_PREFIX))


def reset_channel_bytes():
    with _channel_lock:
        _channel_bytes.clear()


# -- kvstore serialization counters -------------------------------------------
# What the frame layer COSTS, separate from what it MOVES: codec_bytes
# (descriptor bytes emitted by the generated binary codec), pickle_bytes
# (skeleton bytes emitted by the legacy pickle path), send_syscalls
# (socket writes per frame — 1 with vectored sendmsg, 2+N without).
# Deliberately its own dict, not more _channel_bytes kinds: the
# fault-injection tests assert channel counters by exact equality, and
# the hot-path acceptance pin is pickle_bytes == 0 over a measured
# window — bench.py banks both per-step (docs/PERF_NOTES.md round 12).
_serialization: dict = {}
_serialization_lock = threading.Lock()


def record_serialization(kind: str, n: int):
    """Add ``n`` to the serialization counter ``kind`` (always on — a
    dict increment is noise next to the encode it measures)."""
    with _serialization_lock:
        _serialization[kind] = _serialization.get(kind, 0) + int(n)


def serialization_counts() -> dict:
    with _serialization_lock:
        return dict(_serialization)


def codec_bytes_total() -> int:
    """Descriptor bytes emitted by the binary wire codec so far."""
    with _serialization_lock:
        return _serialization.get("codec_bytes", 0)


def pickle_bytes_total() -> int:
    """Skeleton bytes pickled by the legacy frame path so far — the
    steady-state acceptance pin is 0 with the codec negotiated on."""
    with _serialization_lock:
        return _serialization.get("pickle_bytes", 0)


def send_syscalls_total() -> int:
    """Socket write syscalls issued by the frame layer so far."""
    with _serialization_lock:
        return _serialization.get("send_syscalls", 0)


def reset_serialization():
    with _serialization_lock:
        _serialization.clear()


# -- kvstore wire-overlap counters -------------------------------------------
# The fused-dist K-step driver overlaps the push/pull wire round of chunk
# j-1 behind chunk j's scanned compute (docs/PERF_NOTES.md round 10).
# Two clocks make the overlap CPU-testable the way host_syncs made the
# sync-free loop testable:
#   * wire_wait  — host time actually BLOCKED on a pull future (the
#     exposed, un-overlapped part of the wire),
#   * wire_round — full enqueue->resolved time of the same rounds (what
#     the wire costs with no overlap at all).
# overlap_pct = 100*(1 - wait/round) is the regression gate: staleness 0
# (barrier'd chunk boundary) pins it near 0, staleness >= 1 must keep it
# strictly positive whenever compute overlaps any of the round trip —
# ci/run_ci.sh asserts wire_wait_ms strictly below the unoverlapped
# baseline on CPU.
_wire_lock = threading.Lock()
_wire = {"wait_s": 0.0, "round_s": 0.0, "rounds": 0}


def record_wire_wait(dur_s: float):
    """Add host-blocked seconds spent waiting on an in-flight kvstore
    pull (the exposed wire).  Also emitted as a chrome-trace event
    (category "wire") when the profiler is running, so a single-process
    trace shows the wire stall next to the dispatches it blocked —
    these clocks used to feed only the counters and never reached the
    trace export."""
    with _wire_lock:
        _wire["wait_s"] += float(dur_s)
    if _profiler.state == PROFILER_STATE_RUN:
        dur_us = float(dur_s) * 1e6
        _profiler.record("kvstore.wire_wait",
                         time.perf_counter_ns() // 1000 - int(dur_us),
                         dur_us, "wire")


def record_wire_round(dur_s: float):
    """Add one completed wire round's full enqueue->resolved seconds
    (chrome-trace event "wire" category when the profiler runs — see
    record_wire_wait)."""
    with _wire_lock:
        _wire["round_s"] += float(dur_s)
        _wire["rounds"] += 1
    if _profiler.state == PROFILER_STATE_RUN:
        dur_us = float(dur_s) * 1e6
        _profiler.record("kvstore.wire_round",
                         time.perf_counter_ns() // 1000 - int(dur_us),
                         dur_us, "wire")


def wire_wait_ms() -> float:
    with _wire_lock:
        return _wire["wait_s"] * 1e3


def wire_round_ms() -> float:
    with _wire_lock:
        return _wire["round_s"] * 1e3


def wire_rounds() -> int:
    with _wire_lock:
        return _wire["rounds"]


def wire_overlap_pct() -> float:
    """Fraction of the wire hidden behind compute, as a percentage:
    100*(1 - wait/round) over every recorded round, 0.0 before the
    first round (and never negative — scheduling jitter can make a
    single wait marginally exceed its round)."""
    with _wire_lock:
        if _wire["rounds"] == 0 or _wire["round_s"] <= 0.0:
            return 0.0
        return max(0.0, 100.0 * (1.0 - _wire["wait_s"] / _wire["round_s"]))


def reset_wire_counters():
    with _wire_lock:
        _wire["wait_s"] = 0.0
        _wire["round_s"] = 0.0
        _wire["rounds"] = 0


# -- mesh fan-in clock --------------------------------------------------------
# Host time the hierarchy-tier LEADER spends blocked in collect_push
# waiting for every follower's round to arrive — the serialization the
# parallel acceptor pool + shm lane exist to shrink.  bench.py banks
# mesh_fanin_ms_per_step next to shm_bytes_per_step so the acceptors ×
# shm A/B (docs/PERF_NOTES.md round 13) is a regression-gateable number.
_fanin_lock = threading.Lock()
_fanin = {"wait_s": 0.0, "rounds": 0}


def record_mesh_fanin_wait(dur_s: float):
    """Add one collect_push round's blocked seconds (chrome-trace event
    "wire" category when the profiler runs, like the wire clocks)."""
    with _fanin_lock:
        _fanin["wait_s"] += float(dur_s)
        _fanin["rounds"] += 1
    if _profiler.state == PROFILER_STATE_RUN:
        dur_us = float(dur_s) * 1e6
        _profiler.record("kvstore.mesh_fanin",
                         time.perf_counter_ns() // 1000 - int(dur_us),
                         dur_us, "wire")


def mesh_fanin_wait_ms() -> float:
    with _fanin_lock:
        return _fanin["wait_s"] * 1e3


def mesh_fanin_rounds() -> int:
    with _fanin_lock:
        return _fanin["rounds"]


def reset_mesh_fanin():
    with _fanin_lock:
        _fanin["wait_s"] = 0.0
        _fanin["rounds"] = 0


# -- serving latency / QPS counters ------------------------------------------
# Request-latency distributions for the serving tier (mxnet_tpu.serving):
# per KIND (e.g. "serving.request", "serving.batch") a bounded ring of
# duration samples plus completion timestamps.  p50/p99 sit next to
# wire_bytes_per_step on purpose: the serving SLO numbers are first-class
# profiler outputs, not log lines — tests/test_serving.py pins the
# percentile and QPS arithmetic, and ServingReplica's "serving_stats"
# envelope serves these dicts to clients.  Bounded (ring, not full
# history): a replica serving millions of requests must not grow host
# memory with uptime; MXNET_SERVING_LATENCY_WINDOW sizes the ring.
_latency_lock = threading.Lock()
_latency: dict = {}   # kind -> {"durs": deque, "ts": deque, "count", "total"}


def _latency_window() -> int:
    return max(2, int(env("MXNET_SERVING_LATENCY_WINDOW", 2048)))


def record_latency(kind: str, dur_s: float, ts: Optional[float] = None):
    """Record one completed request of ``kind`` taking ``dur_s`` seconds.
    ``ts`` is the completion time (``time.monotonic()`` when omitted —
    injectable so the QPS arithmetic is testable without sleeping)."""
    if ts is None:
        ts = time.monotonic()
    if _profiler.state == PROFILER_STATE_RUN:
        # latency samples used to live only in the percentile ring and
        # never reached the chrome-trace export; emit each completed
        # request as a trace event so a single-process serving trace
        # shows queue-wait + forward time per request
        dur_us = float(dur_s) * 1e6
        _profiler.record(kind,
                         time.perf_counter_ns() // 1000 - int(dur_us),
                         dur_us, "latency")
    with _latency_lock:
        st = _latency.get(kind)
        if st is None:
            from collections import deque
            w = _latency_window()
            st = _latency[kind] = {"durs": deque(maxlen=w),
                                   "ts": deque(maxlen=w),
                                   "count": 0, "total": 0.0}
        st["durs"].append(float(dur_s))
        st["ts"].append(float(ts))
        st["count"] += 1
        st["total"] += float(dur_s)


def percentile(samples, q) -> float:
    """Nearest-rank percentile (q in [0, 100]) over ``samples``.  The
    deterministic textbook definition — sorted sample at rank
    ``ceil(q/100 * n)`` — so the p50/p99 numbers tests pin are exact,
    not interpolation-scheme-dependent."""
    xs = sorted(samples)
    if not xs:
        raise MXNetError("percentile of an empty sample set")
    import math
    rank = max(1, math.ceil((float(q) / 100.0) * len(xs)))
    return xs[min(rank, len(xs)) - 1]


def latency_stats(kind: str) -> Optional[dict]:
    """{count, window, p50_ms, p99_ms, mean_ms, max_ms, qps} for ``kind``
    or None before the first sample.  Percentiles/mean/max are over the
    ring window; ``count``/``total`` are lifetime.  QPS is completions
    over the window's timespan — (len-1)/(last-first), the unbiased
    inter-arrival estimate; 0.0 until two samples exist."""
    with _latency_lock:
        st = _latency.get(kind)
        if st is None:
            return None
        durs = list(st["durs"])
        ts = list(st["ts"])
        count, total = st["count"], st["total"]
    qps = 0.0
    if len(ts) >= 2 and ts[-1] > ts[0]:
        qps = (len(ts) - 1) / (ts[-1] - ts[0])
    return {
        "count": count,
        "window": len(durs),
        "p50_ms": percentile(durs, 50) * 1e3,
        "p99_ms": percentile(durs, 99) * 1e3,
        "mean_ms": (sum(durs) / len(durs)) * 1e3,
        "max_ms": max(durs) * 1e3,
        "qps": qps,
    }


def latency_kinds() -> list:
    with _latency_lock:
        return sorted(_latency)


def reset_latency():
    with _latency_lock:
        _latency.clear()


_NULL = __import__("contextlib").nullcontext()


def scope(name, category="operator", require_mode=None):
    """Context manager for dispatch sites.  Returns a no-op context when
    the profiler is stopped (or the mode doesn't match), so call sites
    are just ``with profiler.scope(...):`` — all gating lives here."""
    if _profiler.state != PROFILER_STATE_RUN:
        return _NULL
    if require_mode is not None and _profiler.mode != require_mode:
        return _NULL
    return _profiler.scope(name, category)


# -- the universal snapshot ---------------------------------------------------
def snapshot(compact: bool = False) -> dict:
    """EVERY counter family in one plain-builtin dict — the single
    source behind the kvstore ``("stats",)`` envelope
    (kvstore_server._stats_payload), ``distributed.cluster_stats()``,
    the elastic beat piggyback and ``python -m mxnet_tpu.profiler
    --dump``, so no consumer can drift from another.

    ``compact=True`` returns only the transport families (channel
    counts/gauges, bytes, wire clocks) — the per-beat piggyback the
    elastic stats bank accumulates; full counters since process start,
    so a lost beat costs freshness, never correctness."""
    out = {
        "channel": channel_counts(),
        "channel_bytes": channel_bytes(),
        "wire": {
            "wait_ms": wire_wait_ms(),
            "round_ms": wire_round_ms(),
            "rounds": wire_rounds(),
            "overlap_pct": wire_overlap_pct(),
        },
    }
    if compact:
        # the health status rides the compact form too: beats piggyback
        # it, so every peer's stats bank holds each member's last-known
        # OK/DEGRADED/CRITICAL verdict next to its counters
        # (docs/OBSERVABILITY.md health section)
        out["health"] = _health.snapshot_section(compact=True)
        return out
    role, rank = tracing.role_rank()
    out.update({
        "pid": os.getpid(),
        "role": role,
        "rank": int(rank or 0),
        "dispatch": dispatch_counts(),
        "host_syncs": host_syncs(),
        "host_sync_total": host_sync_total(),
        "latency": {k: latency_stats(k) for k in latency_kinds()},
        "trace": tracing.stats(),
        "health": _health.snapshot_section(),
    })
    return out


def reset_all():
    """Zero every counter family (the --reset CLI and test isolation;
    the span FILE journal is append-only evidence and stays)."""
    reset_dispatch_counts()
    reset_host_syncs()
    reset_channel_counts()
    reset_channel_bytes()
    reset_wire_counters()
    reset_latency()
    tracing.reset()


def _main(argv=None) -> int:
    """``python -m mxnet_tpu.profiler [--dump] [--reset] [--watch S]``
    — the shell face of :func:`snapshot` for scripts and chip runbooks:
    ``--dump`` (the default) prints the full snapshot as ONE JSON line
    (the same one-line contract bench.py and the autotune executor
    parse); ``--reset`` zeroes the counters first (combine both for a
    read-and-rearm); ``--watch S`` repeats the dump every S seconds —
    one JSON line per tick, same contract — so a chip runbook can tail
    live counters (``| jq .wire``) without writing a loop.  ``--ticks
    N`` bounds the watch (0 = until interrupted)."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.profiler",
        description="dump/reset/watch the mxnet_tpu profiler counter "
                    "snapshot (docs/OBSERVABILITY.md)")
    ap.add_argument("--dump", action="store_true",
                    help="print the snapshot as one JSON line (default "
                         "when --reset is not given)")
    ap.add_argument("--reset", action="store_true",
                    help="zero every counter family")
    ap.add_argument("--watch", type=float, default=None, metavar="S",
                    help="interval mode: print one snapshot JSON line "
                         "every S seconds (ctrl-C to stop)")
    ap.add_argument("--ticks", type=int, default=0, metavar="N",
                    help="with --watch: stop after N lines (0 = run "
                         "until interrupted)")
    args = ap.parse_args(argv)
    if args.watch is not None:
        if args.watch <= 0:
            ap.error("--watch interval must be > 0 seconds")
        if args.reset:
            reset_all()
        tick = 0
        try:
            while True:
                print(json.dumps(snapshot(), sort_keys=True,
                                 default=str), flush=True)
                tick += 1
                if args.ticks and tick >= args.ticks:
                    break
                time.sleep(args.watch)
        except KeyboardInterrupt:
            pass
        return 0
    # dump BEFORE reset: the --dump --reset combination is
    # read-and-rearm — print the accumulated counters, THEN zero them
    # (the other order would print an empty snapshot and lose the data)
    if args.dump or not args.reset:
        print(json.dumps(snapshot(), sort_keys=True, default=str))
    if args.reset:
        reset_all()
    return 0


if env("MXNET_PROFILER_AUTOSTART", 0):
    profiler_set_state("run")


if __name__ == "__main__":
    import sys
    sys.exit(_main())
