"""Training callbacks (reference: python/mxnet/callback.py).

SYNC CONTRACT (the sync-free training loop, docs/PERF_NOTES.md round 8):
metric accumulation in fit/score is device-resident, and a callback that
reads the metric — ``get_name_value()`` → ``EvalMetric.sync()`` — is the
ONLY point where the host blocks on a device readback.  Callbacks that
observe metrics therefore set the loop's sync cadence: Speedometer
syncs once per ``frequent`` batches, LogValidationMetricsCallback once
per evaluation, and a loop with no metric-reading callback syncs once
per epoch (the epoch-end log).  tests/test_sync_free.py asserts this.
"""
from __future__ import annotations

import logging
import math
import time


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end checkpoint callback (reference: callback.py:27)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1, sharded_async=False):
    """reference: callback.py:55 — save symbol+params every `period` epochs.

    ``sharded_async=True`` saves through checkpoint.AsyncCheckpointer
    (sharded format, per-epoch prefixes): the epoch boundary only pays a
    device-side snapshot and training continues while the shards write in
    the background.  The returned callback carries the checkpointer as
    ``_callback.checkpointer`` — call ``.wait()`` after fit() before
    reading the final checkpoint."""
    from .model import save_checkpoint
    period = int(max(1, period))
    if sharded_async:
        from .checkpoint import AsyncCheckpointer
        ck = AsyncCheckpointer()

        def _callback(iter_no, sym, arg, aux):
            if (iter_no + 1) % period == 0:
                ck.save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
        _callback.checkpointer = ck
        return _callback

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_train_metric(period, auto_reset=False):
    """reference: callback.py log_train_metric."""
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info('Iter[%d] Batch[%d] Train-%s=%f',
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer:
    """Throughput logging (reference: callback.py:120 Speedometer).

    Reading the metric here (every ``frequent`` batches) triggers the
    lazy ``EvalMetric.sync()`` — with device-resident metrics this is
    the training loop's ONLY per-interval host sync, so ``frequent`` is
    literally the host-readbacks-per-epoch dial: N batches at
    ``frequent=F`` cost floor((N-1)/F) syncs here (count hits
    ``% F == 0`` on batch indices 1..N-1) plus the epoch-end log's one,
    not N."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count

        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = 'Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec'
                    msg += '\t%s=%f' * len(name_value)
                    logging.info(msg, param.epoch, count, speed,
                                 *sum(name_value, ()))
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """reference: callback.py ProgressBar."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = '=' * filled_len + '-' * (self.bar_len - filled_len)
        logging.info('[%s] %s%s\r', prog_bar, percents, '%')


class LogValidationMetricsCallback:
    """reference: callback.py LogValidationMetricsCallback.

    ``get_name_value()`` below is the lazy sync point: the whole
    validation pass accumulates on device and this callback's read is
    its one host readback."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        name_value = param.eval_metric.get_name_value()
        for name, value in name_value:
            logging.info('Epoch[%d] Validation-%s=%f', param.epoch, name,
                         value)
