"""Reference binary .params compatibility: read AND write the original
dmlc::Stream NDArray container.

A user migrating from the reference brings checkpoints written by
``mx.nd.save`` / ``save_checkpoint`` — this module loads those files and
can write them back, so artifacts round-trip with the original
implementation.  Format studied from reference source (cited per
function); proven against the reference's own checked-in binary fixture
``tests/python/unittest/legacy_ndarray.v0`` (mirrored into
tests/golden/) — real bytes the original implementation produced.

Layout (all little-endian; reference: src/ndarray/ndarray.cc:1022-1050
``NDArray::Save(fo, data, names)``):

  uint64 0x112 (kMXAPINDArrayListMagic), uint64 reserved=0,
  uint64 n_arrays, n x <NDArray>, uint64 n_names, n x (uint64 len, bytes)

Per NDArray (ndarray.cc:826-1010):
  V2 (magic 0xF993FAC9): int32 stype (0 dense / 1 row_sparse / 2 csr,
      ndarray.h:58); [sparse: storage TShape]; TShape shape
      (uint32 ndim + int64 dims); Context (int32 dev_type, int32 dev_id,
      base.h:188); int32 type_flag (mshadow: 0 f32, 1 f64, 2 f16,
      3 u8, 4 i32, 5 i8, 6 i64); [sparse: per-aux int32 type +
      TShape]; raw data; [sparse: aux arrays].
  V1 (magic 0xF993FAC8): shape (uint32 ndim + int64 dims), Context,
      type_flag, raw data (ndarray.cc:892-931 LegacyLoad).
  V0: the leading uint32 IS ndim, dims are uint32 (LegacyTShapeLoad
      default branch), then Context/type/data.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Union

import numpy as np

from .base import MXNetError
from .ndarray import NDArray

_LIST_MAGIC = 0x112
_V2_MAGIC = 0xF993FAC9
_V1_MAGIC = 0xF993FAC8

# mshadow TypeFlag (mshadow/base.h) <-> numpy
_TYPE_FLAGS = {0: np.float32, 1: np.float64, 2: np.float16,
               3: np.uint8, 4: np.int32, 5: np.int8, 6: np.int64}
_FLAG_OF = {np.dtype(v): k for k, v in _TYPE_FLAGS.items()}


class _Reader:
    def __init__(self, buf):
        self.b = buf
        self.o = 0

    def take(self, n):
        if self.o + n > len(self.b):
            raise MXNetError("reference .params: truncated file")
        out = self.b[self.o:self.o + n]
        self.o += n
        return out

    def u32(self):
        return struct.unpack("<I", self.take(4))[0]

    def i32(self):
        return struct.unpack("<i", self.take(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.take(8))[0]


def _read_tshape(r, ndim=None, dim64=True):
    if ndim is None:
        ndim = r.u32()
    fmt, sz = ("<q", 8) if dim64 else ("<I", 4)
    return tuple(struct.unpack(fmt, r.take(sz))[0] for _ in range(ndim))


def _read_context(r):
    r.i32()  # dev_type — irrelevant here; everything loads to our runtime
    r.i32()  # dev_id


def _read_array_data(r, shape, flag):
    dt = np.dtype(_TYPE_FLAGS.get(flag))
    if flag not in _TYPE_FLAGS:
        raise MXNetError(f"reference .params: unknown type flag {flag}")
    n = int(np.prod(shape)) if shape else 1
    raw = r.take(n * dt.itemsize)
    return np.frombuffer(raw, dtype=dt).reshape(shape).copy()


def _read_one(r):
    magic = r.u32()
    if magic == _V2_MAGIC:
        stype = r.i32()
        nad = {0: 0, 1: 1, 2: 2}.get(stype)
        if nad is None:
            raise MXNetError(
                f"reference .params: unknown storage type {stype}")
        sshape = _read_tshape(r) if nad else None
        shape = _read_tshape(r)
        if len(shape) == 0:
            return NDArray(np.zeros((), np.float32))
        _read_context(r)
        flag = r.i32()
        aux = [(r.i32(), _read_tshape(r)) for _ in range(nad)]
        data = _read_array_data(r, sshape if nad else shape, flag)
        aux_arrays = [_read_array_data(r, ashape, aflag)
                      for aflag, ashape in aux]
        if nad == 0:
            return NDArray(data)
        return _to_sparse(stype, shape, data, aux_arrays)
    if magic == _V1_MAGIC:
        shape = _read_tshape(r)
    else:
        # V0: the magic we just consumed IS ndim; uint32 dims
        shape = _read_tshape(r, ndim=magic, dim64=False)
    if len(shape) == 0:
        return NDArray(np.zeros((), np.float32))
    _read_context(r)
    flag = r.i32()
    return NDArray(_read_array_data(r, shape, flag))


def _to_sparse(stype, shape, data, aux_arrays):
    from .ndarray import sparse as sp
    if stype == 1:   # row_sparse: aux = [indices] (ndarray.h RowSparseAux)
        return sp.RowSparseNDArray(data, aux_arrays[0], shape)
    # csr: aux order in the file is [indptr, indices] (ndarray.h CSRAux)
    return sp.CSRNDArray(data, aux_arrays[1], aux_arrays[0], shape)


def is_reference_format(fname: str) -> bool:
    with open(fname, "rb") as f:
        head = f.read(8)
    return len(head) == 8 and \
        struct.unpack("<Q", head)[0] == _LIST_MAGIC


def load_reference_params(fname: str) \
        -> Union[List[NDArray], Dict[str, NDArray]]:
    """Load a reference-written ``.params`` / ``mx.nd.save`` file."""
    with open(fname, "rb") as f:
        r = _Reader(f.read())
    if r.u64() != _LIST_MAGIC:
        raise MXNetError(f"{fname}: not a reference NDArray file")
    r.u64()  # reserved
    n = r.u64()
    arrays = [_read_one(r) for _ in range(n)]
    n_names = r.u64()
    if n_names == 0:
        return arrays
    if n_names != n:
        raise MXNetError(f"{fname}: {n_names} names for {n} arrays")
    names = [r.take(r.u64()).decode() for _ in range(n_names)]
    return dict(zip(names, arrays))


def save_reference_params(fname: str, data) -> None:
    """Write dense NDArrays in the reference's V2 container so the
    ORIGINAL implementation can load them (migration in both
    directions).  bfloat16 upcasts to float32 (no bf16 in the 2017
    format — documented lossy widening, never silent truncation)."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    chunks = [struct.pack("<QQQ", _LIST_MAGIC, 0, len(arrays))]
    for i, arr in enumerate(arrays):
        a = np.asarray(getattr(arr, "_data", arr))
        if a.ndim == 0:
            # every reader (ours AND the reference's NDArray::Load)
            # treats ndim==0 as "empty, nothing follows" — writing data
            # after it would desynchronize the stream
            raise MXNetError(
                "save_reference_params: 0-d arrays cannot be represented "
                "in the reference format (entry %s); reshape to (1,)"
                % (names[i] if names else i))
        if a.dtype not in _FLAG_OF:
            if str(a.dtype) == "bfloat16":
                a = a.astype(np.float32)   # no bf16 in the 2017 format:
                # documented lossy WIDENING (exact for every bf16 value)
            else:
                raise MXNetError(
                    "save_reference_params: dtype %s has no reference "
                    "type flag (entry %s)"
                    % (a.dtype, names[i] if names else i))
        chunks.append(struct.pack("<Ii", _V2_MAGIC, 0))        # dense
        chunks.append(struct.pack("<I", a.ndim))
        chunks.append(struct.pack("<%dq" % a.ndim, *a.shape))
        chunks.append(struct.pack("<ii", 1, 0))                # cpu(0)
        chunks.append(struct.pack("<i", _FLAG_OF[np.dtype(a.dtype)]))
        chunks.append(np.ascontiguousarray(a).tobytes())
    chunks.append(struct.pack("<Q", len(names)))
    for nm in names:
        b = nm.encode()
        chunks.append(struct.pack("<Q", len(b)) + b)
    with open(fname, "wb") as f:
        f.write(b"".join(chunks))
