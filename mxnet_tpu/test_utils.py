"""Testing utilities (reference: python/mxnet/test_utils.py, 1.4k LoC —
the backbone of the reference's entire test strategy, SURVEY.md §4).

Key entry points kept API-compatible:
``check_numeric_gradient`` (test_utils.py:789) — finite differences vs
symbolic gradients; ``check_symbolic_forward/backward`` (:921, :995) —
vs a numpy reference; ``check_consistency`` (:1203) — the same symbol run
across contexts/dtypes and cross-asserted; ``default_context`` (:50)
switches the whole suite's device.
"""
from __future__ import annotations

import numbers

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .executor import Executor
from .ndarray import NDArray
from .ndarray.ndarray import array as nd_array
from .symbol import Symbol

_default_ctx = None


def default_context() -> Context:
    """reference: test_utils.py:50."""
    return _default_ctx or current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def default_dtype():
    return np.float32


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(np.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(np.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_ndarray(shape, stype='default', density=None, dtype=None):
    """reference: test_utils.py rand_ndarray."""
    if stype == 'default':
        return nd_array(np.random.uniform(-1, 1, shape).astype(
            dtype or np.float32))
    from .ndarray import sparse
    return sparse.rand_sparse_ndarray(shape, stype, density=density,
                                      dtype=dtype)[0]


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """reference: test_utils.py np_reduce."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else \
            range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def _parse_tols(dtype, rtol, atol):
    # reference: test_utils.py:68-80 per-dtype default tolerances
    defaults = {np.dtype(np.float16): (1e-2, 1e-4),
                np.dtype(np.float32): (1e-4, 1e-6),
                np.dtype(np.float64): (1e-5, 1e-8)}
    drt, dat = defaults.get(np.dtype(dtype) if dtype else
                            np.dtype(np.float32), (1e-4, 1e-6))
    return rtol if rtol is not None else drt, \
        atol if atol is not None else dat


def assert_almost_equal(a, b, rtol=None, atol=None, names=('a', 'b'),
                        equal_nan=False):
    """reference: test_utils.py:467."""
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    rtol, atol = _parse_tols(a.dtype, rtol, atol)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               equal_nan=equal_nan,
                               err_msg=f'{names[0]} vs {names[1]}')


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def same_array(array1, array2):
    """Same underlying buffer (reference: test_utils.py same_array) —
    jax arrays are immutable so identity of the payload is the test."""
    return array1._data is array2._data


def _bind(sym, location, aux_states=None, grad_req='write', ctx=None):
    arg_names = sym.list_arguments()
    if isinstance(location, dict):
        args = {k: (v if isinstance(v, NDArray) else nd_array(v))
                for k, v in location.items()}
    else:
        args = {n: (v if isinstance(v, NDArray) else nd_array(v))
                for n, v in zip(arg_names, location)}
    aux = None
    if aux_states is not None:
        aux_names = sym.list_auxiliary_states()
        if isinstance(aux_states, dict):
            aux = {k: (v if isinstance(v, NDArray) else nd_array(v))
                   for k, v in aux_states.items()}
        else:
            aux = {n: (v if isinstance(v, NDArray) else nd_array(v))
                   for n, v in zip(aux_names, aux_states)}
    grads = {n: nd_array(np.zeros(args[n].shape, dtype=args[n].dtype))
             for n in arg_names if grad_req != 'null'}
    ex = Executor(sym, ctx or default_context(), args=args,
                  args_grad=grads if grads else None, grad_req=grad_req,
                  aux_states=aux)
    return ex


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """reference: test_utils.py simple_forward."""
    ex = _bind(sym, inputs, grad_req='null', ctx=ctx)
    outputs = [o.asnumpy() for o in ex.forward(is_train=is_train)]
    return outputs[0] if len(outputs) == 1 else outputs


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Finite-difference gradients (reference: test_utils.py:744)."""
    approx_grads = {}
    for k in sorted(location):
        val = location[k]
        if not np.issubdtype(np.asarray(val).dtype, np.floating):
            continue
        old = np.asarray(val, dtype=np.float64).copy()
        grad = np.zeros_like(old).ravel()
        flat = old.ravel()
        for i in range(flat.size):
            base = flat[i]
            flat[i] = base + eps / 2
            executor.arg_dict[k]._set_data(
                np.asarray(old.astype(np.float32)))
            fp = executor.forward(is_train=use_forward_train)
            fplus = fp[0].asnumpy().sum()
            flat[i] = base - eps / 2
            executor.arg_dict[k]._set_data(
                np.asarray(old.astype(np.float32)))
            fm = executor.forward(is_train=use_forward_train)
            fminus = fm[0].asnumpy().sum()
            grad[i] = (fplus - fminus) / eps
            flat[i] = base
        executor.arg_dict[k]._set_data(np.asarray(old.astype(np.float32)))
        approx_grads[k] = grad.reshape(old.shape)
    return approx_grads


def check_numeric_gradient(sym, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, use_forward_train=True,
                           ctx=None, grad_stype_dict=None,
                           dtype=np.float32):
    """Finite-difference vs autodiff gradients
    (reference: test_utils.py:789)."""
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    location = {k: np.asarray(v, dtype=dtype) for k, v in location.items()}
    if grad_nodes is None:
        grad_nodes = [k for k, v in location.items()
                      if np.issubdtype(np.asarray(v).dtype, np.floating)]

    # random projection to a scalar head so d(head)/dx is well defined
    # (reference builds sum(out * random_proj))
    out = sym
    ex = _bind(out, location, aux_states, ctx=ctx)
    outs = ex.forward(is_train=use_forward_train)
    proj = [np.random.uniform(-1, 1, o.shape).astype(dtype) for o in outs]
    ex.backward(out_grads=[nd_array(p) for p in proj])
    sym_grads = {k: ex.grad_dict[k].asnumpy() for k in grad_nodes
                 if ex.grad_dict.get(k) is not None}

    # numeric: f = sum(out_i * proj_i); reuse ONE bound executor and only
    # swap the perturbed arg — same shapes, so the jitted program is
    # compiled once (the per-probe rebind would recompile 2N times)
    def f_of(k, arr):
        ex.arg_dict[k]._set_data(np.asarray(arr.astype(dtype)))
        os_ = ex.forward(is_train=use_forward_train)
        return sum(float((o.asnumpy() * p).sum())
                   for o, p in zip(os_, proj))

    for k in grad_nodes:
        old = location[k].astype(np.float64).copy()
        ngrad = np.zeros_like(old).ravel()
        flat = old.ravel()
        for i in range(flat.size):
            base = flat[i]
            flat[i] = base + numeric_eps / 2
            fplus = f_of(k, old)
            flat[i] = base - numeric_eps / 2
            fminus = f_of(k, old)
            ngrad[i] = (fplus - fminus) / numeric_eps
            flat[i] = base
        ex.arg_dict[k]._set_data(np.asarray(old.astype(dtype)))
        ngrad = ngrad.reshape(old.shape)
        assert_almost_equal(ngrad, sym_grads[k], rtol=rtol,
                            atol=atol if atol is not None else 1e-4,
                            names=(f'numeric_{k}', f'symbolic_{k}'))


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None, dtype=np.float32,
                           equal_nan=False):
    """reference: test_utils.py:921."""
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    location = {k: np.asarray(v, dtype=dtype)
                if np.issubdtype(np.asarray(v).dtype, np.floating)
                else np.asarray(v) for k, v in location.items()}
    ex = _bind(sym, location, aux_states, grad_req='null', ctx=ctx)
    outputs = ex.forward(is_train=False)
    if isinstance(expected, dict):
        expected = [expected[n] for n in sym.list_outputs()]
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out, exp, rtol=rtol, atol=atol,
                            names=('output', 'expected'),
                            equal_nan=equal_nan)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected,
                            rtol=1e-5, atol=None, aux_states=None,
                            grad_req='write', ctx=None, dtype=np.float32,
                            equal_nan=False):
    """reference: test_utils.py:995."""
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    location = {k: np.asarray(v, dtype=dtype)
                if np.issubdtype(np.asarray(v).dtype, np.floating)
                else np.asarray(v) for k, v in location.items()}
    ex = _bind(sym, location, aux_states, grad_req=grad_req, ctx=ctx)
    ex.forward(is_train=True)
    ex.backward(out_grads=[nd_array(np.asarray(g, dtype=dtype))
                           for g in out_grads])
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    grads = {k: v.asnumpy() for k, v in ex.grad_dict.items()
             if v is not None}
    for name, exp in expected.items():
        assert_almost_equal(grads[name], exp, rtol=rtol, atol=atol,
                            names=(f'grad_{name}', f'expected_{name}'),
                            equal_nan=equal_nan)
    return grads


def check_consistency(sym, ctx_list, scale=1.0, grad_req='write',
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, ground_truth=None):
    """Run the symbol on every (ctx, dtype) config and cross-assert
    (reference: test_utils.py:1203 — the GPU/CPU, fp16/fp32 matrix)."""
    if tol is None:
        tol = {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-3,
               np.dtype(np.float64): 1e-5}
    elif isinstance(tol, numbers.Number):
        tol = {np.dtype(np.float16): tol, np.dtype(np.float32): tol,
               np.dtype(np.float64): tol}
    assert len(ctx_list) > 1
    if isinstance(sym, Symbol):
        sym = [sym] * len(ctx_list)
    else:
        assert len(sym) == len(ctx_list)

    output_points = sym[0].list_outputs()
    arg_names = sym[0].list_arguments()
    arg_shapes, _, aux_shapes = sym[0].infer_shape(
        **{k: v for k, v in ctx_list[0].items() if k != 'ctx'
           and k != 'type_dict' and isinstance(v, tuple)})
    rng = np.random.RandomState(0)
    base_args = {n: rng.normal(0, scale, s).astype(np.float64)
                 for n, s in zip(arg_names, arg_shapes)}
    if arg_params:
        base_args.update({k: np.asarray(v, np.float64)
                          for k, v in arg_params.items()})
    base_aux = {n: np.zeros(s) for n, s in
                zip(sym[0].list_auxiliary_states(), aux_shapes)}
    if aux_params:
        base_aux.update({k: np.asarray(v, np.float64)
                         for k, v in aux_params.items()})

    results = []
    dtypes = []
    for s, config in zip(sym, ctx_list):
        ctx = config.get('ctx', default_context())
        type_dict = config.get('type_dict', {})
        dtype = np.dtype(list(type_dict.values())[0]) if type_dict \
            else np.dtype(np.float32)
        dtypes.append(dtype)
        args = {k: v.astype(type_dict.get(k, np.float32))
                for k, v in base_args.items()}
        aux = {k: v.astype(np.float32) for k, v in base_aux.items()}
        ex = _bind(s, args, aux, grad_req=grad_req, ctx=ctx)
        outs = ex.forward(is_train=True)
        # cotangents must match each output's dtype (fp16 configs
        # produce fp16 outputs)
        ex.backward(out_grads=[
            nd_array(np.ones(o.shape, dtype=o.dtype)) for o in outs])
        results.append({
            'outputs': [o.asnumpy().astype(np.float64) for o in outs],
            'grads': {k: v.asnumpy().astype(np.float64)
                      for k, v in ex.grad_dict.items() if v is not None},
        })

    # compare every config against the most precise one
    gt_idx = int(np.argmax([np.dtype(d).itemsize for d in dtypes]))
    gt = ground_truth or results[gt_idx]
    for i, (res, dtype) in enumerate(zip(results, dtypes)):
        if res is gt:
            continue
        t = tol[np.dtype(dtype)]
        try:
            for o, og in zip(res['outputs'], gt['outputs']):
                np.testing.assert_allclose(o, og, rtol=t, atol=t)
            for k in res['grads']:
                np.testing.assert_allclose(res['grads'][k],
                                           gt['grads'][k], rtol=t, atol=t)
        except AssertionError:
            if raise_on_err:
                raise
    return results


def check_speed(sym, location=None, ctx=None, N=20, grad_req='write',
                typ='whole', **kwargs):
    """Time forward(+backward) throughput (reference: test_utils.py:1129)."""
    import time
    if location is None:
        arg_shapes, _, _ = sym.infer_shape(**kwargs)
        location = {k: np.random.normal(size=s, scale=1.0).astype(
            np.float32) for k, s in zip(sym.list_arguments(), arg_shapes)}
    ex = _bind(sym, location, grad_req=grad_req, ctx=ctx)
    if typ == 'whole':
        def run():
            outs = ex.forward(is_train=True)
            ex.backward(out_grads=[
                nd_array(np.ones(o.shape, np.float32)) for o in outs])
    elif typ == 'forward':
        def run():
            ex.forward(is_train=False)[0].asnumpy()
    else:
        raise MXNetError(f'typ must be whole/forward, got {typ!r}')
    run()  # warm up / compile
    tic = time.time()
    for _ in range(N):
        run()
    if typ == 'whole':
        ex.grad_dict[sym.list_arguments()[0]].asnumpy()
    return (time.time() - tic) / N


def retry(n):
    """Decorator: retry flaky tests n times (reference: test_utils.py:550)."""
    assert n > 0

    def decorate(f):
        import functools

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
        return wrapper
    return decorate
