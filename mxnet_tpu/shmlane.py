"""Same-host shared-memory lane for the hierarchical mesh tier.

The hierarchy tier (MXNET_KVSTORE_HIERARCHY, docs/PERF_NOTES.md round
11) moves gradient bytes off the TCP wire onto the in-host mesh — but
the mesh CHANNEL itself still rode TCP loopback, paying two kernel
copies and a syscall per frame for bytes that never leave the host.
This module is the hardware-speed replacement: one POSIX shared-memory
segment per follower data connection holding a pair of SPSC byte rings
(follower→leader requests, leader→follower replies), carrying the
EXACT frame bytes the socket would (wirecodec v2 binary frames or the
legacy pickle frames, first byte self-discriminating) so envelope,
dedup and replay semantics are untouched — a frame is one memcpy into
the ring and zero socket syscalls (`profiler.send_syscalls` counts
only socket writes, which is the acceptance pin).

**Negotiation** (`shm_hello`, a first-class wire op in the protocol
table): the FOLLOWER creates the segment right after the mesh channel
dials, then sends ``("shm_hello", <segment name>)`` enveloped over the
socket; a leader that can attach replies the lane version and serves
that connection's later frames from the ring, a leader that can't
(cross-host peer — the segment name doesn't resolve — or an old
leader that errs on the unknown op) leaves the connection on TCP.
``MXNET_KVSTORE_SHM`` gates the attempt: ``auto`` (default) tries when
the mesh endpoint is a local address, ``on``/``1`` always tries,
``off``/``0`` never.

**Window-1 contract.**  Mesh channels run a one-envelope window
(kvstore._ServerConn window=1), so requests and replies strictly
alternate: each ring holds at most one frame at a time, a frame too
big for the ring simply rides the socket for that round (no
reordering is possible with one envelope in flight), and ring-full
can't happen.  The lane refuses wider windows.

**Failure = the transport the channel already survives.**  A wedged
leader drain (injectable: MXNET_FI_SHM_WEDGE_AFTER) leaves the
follower's request sitting unconsumed; the follower's stall watchdog
(MXNET_KVSTORE_SHM_STALL_S) marks the lane dead in the shared header
and surfaces a ConnectionError into the ordinary reconnect path — the
channel re-dials a fresh socket and REPLAYS its window over TCP, and
the leader's per-client dedup keeps the replay exactly-once.  Closing
the old socket is what makes duplicate replies impossible: any reply
the leader raced onto the dying lane/socket dies with them.

**Ring layout** (all little-endian, u32 free-running indices):

    header[64]: magic 'MXSL' | version | flags (bit0 = lane dead) | _
                req ring desc {data_off, cap, widx, ridx}
                rsp ring desc {data_off, cap, widx, ridx}
    records:    u32 length | payload   (one wire frame per record)
                length 0xFFFFFFFF = wrap marker (skip to ring start);
                a tail gap < 4 bytes is an implicit skip both sides
                compute.

Indices are free-running mod 2^32 (u32 stores are single aligned
writes — never torn); the writer publishes payload bytes BEFORE its
widx store and the reader advances ridx only after copying out, which
on x86-TSO (and through the GIL in-process) is the whole memory-order
story.  Each ring is strictly single-producer/single-consumer: the
follower's IO thread vs the leader's acceptor thread that owns the
connection.
"""
from __future__ import annotations

import struct
import time

from .analysis import hb as _hb
from .base import MXNetError, env as _env

VERSION = 1
_MAGIC = 0x4D58534C          # 'MXSL'
_HEADER = 64
_WRAP = 0xFFFFFFFF
_M32 = 0xFFFFFFFF
_FLAG_DEAD = 0x1
# desc field offsets inside a 16-byte ring descriptor
_D_DATA, _D_CAP, _D_WIDX, _D_RIDX = 0, 4, 8, 12
_REQ_DESC, _RSP_DESC = 16, 32


def mode() -> str:
    """Normalized MXNET_KVSTORE_SHM: 'auto' | 'on' | 'off'."""
    raw = str(_env("MXNET_KVSTORE_SHM", "auto")).strip().lower()
    if raw in ("1", "on", "true", "yes"):
        return "on"
    if raw in ("0", "off", "false", "no", "none"):
        return "off"
    return "auto"


def _is_local_host(host: str) -> bool:
    """Best-effort 'does this mesh endpoint live on THIS host'.  The
    cheap pre-filter for auto mode only: a wrong True still fails
    safe (the leader's attach raises, the err reply keeps the
    connection on TCP), a wrong False just skips the optimization."""
    import socket
    h = (host or "").strip().lower()
    if h in ("localhost", "::1", "0.0.0.0", "") or h.startswith("127."):
        return True
    try:
        if h == socket.gethostname().lower():
            return True
        local = socket.gethostbyname_ex(socket.gethostname())[2]
        return socket.gethostbyname(h) in local
    except OSError:
        return False


def client_enabled(host: str) -> bool:
    """Should a follower ATTEMPT the lane against this mesh host?"""
    m = mode()
    if m == "off":
        return False
    if m == "on":
        return True
    return _is_local_host(host)


def ring_bytes() -> int:
    return max(64 * 1024,
               int(_env("MXNET_KVSTORE_SHM_RING_KB", 4096)) * 1024)


class _Ring:
    """One SPSC byte ring over a slice of the shared segment.  Not an
    owner — just index arithmetic over the lane's buffer; `desc` is
    the byte offset of its {data_off, cap, widx, ridx} descriptor."""

    __slots__ = ("_buf", "_desc", "_data", "_cap", "_tag")

    def __init__(self, buf, desc, tag=""):
        self._buf = buf
        self._desc = desc
        self._data = struct.unpack_from("<I", buf, desc + _D_DATA)[0]
        self._cap = struct.unpack_from("<I", buf, desc + _D_CAP)[0]
        self._tag = tag    # "<segment>.req" / "<segment>.rsp"

    @staticmethod
    def format(buf, desc, data_off, cap):
        struct.pack_into("<IIII", buf, desc, data_off, cap, 0, 0)

    def _widx(self):
        return struct.unpack_from("<I", self._buf, self._desc + _D_WIDX)[0]

    def _ridx(self):
        return struct.unpack_from("<I", self._buf, self._desc + _D_RIDX)[0]

    @property
    def cap(self):
        return self._cap

    def backlog(self) -> int:
        """Unconsumed bytes (record framing included)."""
        return (self._widx() - self._ridx()) & _M32

    def reader_pos(self) -> int:
        """The consumer's free-running index — the follower's stall
        watchdog snapshots it to see whether the leader is draining."""
        return self._ridx()

    def try_push(self, parts, total) -> bool:
        """Write one record (``parts`` concatenated, ``total`` bytes)
        or return False when it can't fit RIGHT NOW (window-1 traffic
        means that only ever happens for a frame bigger than the
        ring).  Single producer: only the channel's IO thread calls
        this."""
        cap = self._cap
        if total + 4 > cap:
            return False
        widx, ridx = self._widx(), self._ridx()
        # the ring is deliberately lock-free: the one invariant is one
        # writer thread per index, and the probe sits inside the
        # read-indices -> publish-widx window so the controlled
        # scheduler can preempt exactly there
        _hb.note_spsc(("shmring", self._tag, "widx"),
                      "shmlane.%s.widx" % (self._tag or "ring"), True)
        free = cap - ((widx - ridx) & _M32)
        pos = widx % cap
        room = cap - pos
        skip = 0
        if room < 4 + total:
            skip = room          # wrap: pad the tail, restart at 0
            pos = 0
        if free < skip + 4 + total:
            return False
        buf = self._buf
        if skip >= 4:
            struct.pack_into("<I", buf, self._data + (widx % cap), _WRAP)
        # payload before the length prefix is visible?  Order doesn't
        # matter within the record — the reader only looks past ridx
        # after the widx store below publishes the whole record.
        struct.pack_into("<I", buf, self._data + pos, total)
        off = self._data + pos + 4
        for p in parts:
            m = memoryview(p)
            n = m.nbytes
            if not n:    # casting a 0-in-shape ndarray view raises
                continue
            buf[off:off + n] = m.cast("B")
            off += n
        struct.pack_into("<I", buf, self._desc + _D_WIDX,
                         (widx + skip + 4 + total) & _M32)
        return True

    def try_pop(self):
        """Pop one whole record as bytes, or None when the ring is
        empty.  Single consumer: only the acceptor thread owning the
        connection (leader side) / the IO thread (follower side)."""
        buf = self._buf
        cap = self._cap
        while True:
            widx, ridx = self._widx(), self._ridx()
            _hb.note_spsc(("shmring", self._tag, "ridx"),
                          "shmlane.%s.ridx" % (self._tag or "ring"),
                          True)
            used = (widx - ridx) & _M32
            if used == 0:
                return None
            pos = ridx % cap
            room = cap - pos
            if room < 4:
                # implicit tail skip (writer never starts a prefix here)
                struct.pack_into("<I", buf, self._desc + _D_RIDX,
                                 (ridx + room) & _M32)
                continue
            length = struct.unpack_from("<I", buf, self._data + pos)[0]
            if length == _WRAP:
                struct.pack_into("<I", buf, self._desc + _D_RIDX,
                                 (ridx + room) & _M32)
                continue
            if length + 4 > used or length + 4 > room:
                raise MXNetError(
                    f"shm ring corruption: record length {length} "
                    f"exceeds ring state (used={used}, room={room})")
            rec = bytes(buf[self._data + pos + 4:
                            self._data + pos + 4 + length])
            struct.pack_into("<I", buf, self._desc + _D_RIDX,
                             (ridx + 4 + length) & _M32)
            return rec


# segments created by THIS process — an in-process attach (tests run
# leader and follower in one interpreter) must not unregister a name
# the creator side still owns with the resource tracker
_CREATED_HERE: set = set()


class ShmLane:
    """One follower<->leader lane: a shared segment with the request
    and reply rings.  ``create`` (follower, owns/unlinks the segment)
    or ``attach`` (leader) — see the module docstring for the
    protocol."""

    def __init__(self, shm, created):
        self._shm = shm
        self._buf = shm.buf
        self.created = created
        self.name = shm.name
        self._closed = False
        self._stall = None     # (reader_pos snapshot, monotonic)
        if created:
            cap = (shm.size - _HEADER) // 2
            cap -= cap % 8
            _Ring.format(self._buf, _REQ_DESC, _HEADER, cap)
            _Ring.format(self._buf, _RSP_DESC, _HEADER + cap, cap)
            struct.pack_into("<IIII", self._buf, 0,
                             _MAGIC, VERSION, 0, 0)
        else:
            magic, version = struct.unpack_from("<II", self._buf, 0)
            if magic != _MAGIC:
                raise MXNetError(
                    f"shm lane {shm.name}: bad magic 0x{magic:08x}")
            if version != VERSION:
                raise MXNetError(
                    f"shm lane {shm.name}: version {version} != "
                    f"{VERSION} (mixed builds on one host?)")
        self._req = _Ring(self._buf, _REQ_DESC, "%s.req" % shm.name)
        self._rsp = _Ring(self._buf, _RSP_DESC, "%s.rsp" % shm.name)

    # -- lifecycle --------------------------------------------------------
    @classmethod
    def create(cls, nbytes=None):
        """Follower side: allocate a fresh auto-named segment holding
        both rings (the name travels in shm_hello)."""
        from multiprocessing import shared_memory
        size = _HEADER + 2 * max(8 * 1024,
                                 (nbytes or ring_bytes()))
        shm = shared_memory.SharedMemory(create=True, size=size)
        _CREATED_HERE.add(shm.name)
        return cls(shm, created=True)

    @classmethod
    def attach(cls, name):
        """Leader side: map the follower's segment by name.  Raises
        (FileNotFoundError and friends) for a cross-host peer — the
        caller errs the hello and the connection stays on TCP.  The
        attacher must NOT be tracked by multiprocessing's resource
        tracker: on this Python, SharedMemory registers every mapping
        unconditionally, and a tracked attacher exiting would unlink a
        segment its creator still owns (plus leak warnings)."""
        from multiprocessing import shared_memory
        shm = shared_memory.SharedMemory(name=name)
        if shm.name not in _CREATED_HERE:
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:  # noqa: BLE001 — tracker detail, best-effort
                pass
        return cls(shm, created=False)

    def mark_dead(self):
        """Publish lane death in the shared header — both sides poll
        it; the survivor stops serving the rings immediately."""
        if self._closed:
            return
        # sticky monotonic bit BOTH sides may set — a yield point but
        # not a single-writer probe
        _hb.note_spsc(("shmdead", self.name), "shmlane.dead", False)
        try:
            flags = struct.unpack_from("<I", self._buf, 8)[0]
            struct.pack_into("<I", self._buf, 8, flags | _FLAG_DEAD)
        except (ValueError, struct.error):
            pass

    def dead(self) -> bool:
        if self._closed:
            return True
        _hb.note_spsc(("shmdead", self.name), "shmlane.dead", False)
        try:
            return bool(struct.unpack_from("<I", self._buf, 8)[0]
                        & _FLAG_DEAD)
        except (ValueError, struct.error):
            return True

    def close(self):
        """Unmap this side's view (idempotent).  The creator's close
        also unlinks — see destroy."""
        if self._closed:
            return
        self._closed = True
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass

    def destroy(self):
        """Tear the lane down for good: unmap, and (creator only)
        unlink the segment name.  The leader's mapping — if any —
        stays valid until its own close; POSIX keeps unlinked segments
        alive while mapped."""
        self.close()
        if self.created:
            try:
                self._shm.unlink()
            except (OSError, FileNotFoundError):
                pass
            _CREATED_HERE.discard(self.name)

    # -- frame traffic ----------------------------------------------------
    def _send(self, ring, kind, obj, binary_ok) -> bool:
        from . import profiler as _prof
        from .kvstore_server import _frame_parts
        if self._closed or self.dead():
            return False
        parts, frame_bytes, codec_bytes, pickle_bytes = _frame_parts(
            obj, binary_ok)
        try:
            if not ring.try_push(parts, frame_bytes):
                return False    # oversized frame: this round rides TCP
        except (ValueError, struct.error):
            return False        # buffer yanked under us (teardown race)
        if codec_bytes:
            _prof.record_serialization("codec_bytes", codec_bytes)
        if pickle_bytes:
            _prof.record_serialization("pickle_bytes", pickle_bytes)
        # ring bytes land in the shm_ family; NO send_syscalls — the
        # whole point is that nothing crossed a socket
        _prof.record_channel_bytes(kind, frame_bytes)
        return True

    def _recv(self, ring, kind):
        from . import profiler as _prof
        from . import wirecodec as _codec
        from .kvstore_server import _frame_obj
        if self._closed:
            return None
        rec = ring.try_pop()
        if rec is None:
            return None
        if len(rec) < 13 or _codec.frame_len(rec[:13]) != len(rec):
            raise MXNetError(
                f"shm lane {self.name}: ring record of {len(rec)} bytes "
                f"is not one wire frame — lane corrupt")
        _prof.record_channel_bytes(kind, len(rec))
        return _frame_obj(rec)

    def send_request(self, obj, binary_ok=True) -> bool:
        """Follower→leader.  True = the frame is in the ring."""
        return self._send(self._req, "shm_sent", obj, binary_ok)

    def recv_request(self):
        """Leader side: pop one request frame, or None.  The armed
        MXNET_FI_SHM_WEDGE_AFTER plan gates each would-succeed pop."""
        from . import faultinject
        if self._closed or self._req.backlog() == 0:
            return None
        if not faultinject.shm_drain_gate():
            return None
        return self._recv(self._req, "shm_recv")

    def send_reply(self, obj, binary_ok=True) -> bool:
        """Leader→follower.  False = caller replies over the socket."""
        return self._send(self._rsp, "shm_sent", obj, binary_ok)

    def recv_reply(self):
        return self._recv(self._rsp, "shm_recv")

    # -- follower stall watchdog ------------------------------------------
    def request_backlog(self) -> int:
        return self._req.backlog()

    def drain_stalled(self, budget_s: float) -> bool:
        """True when the request ring has sat NON-EMPTY with no reader
        progress for ``budget_s`` seconds — the leader stopped
        draining (wedged, descheduled for good, or dead without
        closing).  Progress resets the clock, an empty ring clears
        it."""
        if self._req.backlog() == 0:
            self._stall = None
            return False
        pos = self._req.reader_pos()
        now = time.monotonic()
        if self._stall is None or self._stall[0] != pos:
            self._stall = (pos, now)
            return False
        return (now - self._stall[1]) > budget_s
