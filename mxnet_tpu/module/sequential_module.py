"""SequentialModule: a chain of Modules executed back to back.

Reference: python/mxnet/module/sequential_module.py — each sub-module's
outputs feed the next one's data; ``META_TAKE_LABELS`` marks which
sub-module consumes the labels, ``META_AUTO_WIRING`` wires output names to
the next module's data names automatically.  TPU note: each sub-module
keeps its own fused jit step; the chain boundary materializes activations
(exactly the reference semantics, where each sub-module is an independent
executor) — a single-symbol Module remains the fully-fused fast path.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..initializer import Uniform
from .base_module import BaseModule


class SequentialModule(BaseModule):
    """reference: sequential_module.py SequentialModule."""

    META_TAKE_LABELS = 'take_labels'
    META_AUTO_WIRING = 'auto_wiring'

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None
        self._meta_keys = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}

    def add(self, module, **kwargs):
        """Add a sub-module with meta flags (take_labels, auto_wiring)."""
        self._modules.append(module)
        for k in kwargs:
            if k not in self._meta_keys:
                raise MXNetError(f"unknown meta key {k!r}; "
                                 f"valid: {sorted(self._meta_keys)}")
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self  # chaining, as the reference allows

    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for m in self._modules:
            arg, aux = m.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        # each sub-module only sees its own subset, so missing/extra-name
        # enforcement must happen here across the union
        wanted = set()
        wanted_aux = set()
        for m in self._modules:
            wanted.update(getattr(m, '_param_names', []))
            wanted_aux.update(getattr(m, '_aux_names', []))
        if not allow_missing:
            missing = sorted(wanted - set(arg_params)) \
                if arg_params is not None else []
            missing += sorted(wanted_aux - set(aux_params)) \
                if aux_params is not None else []
            if missing:
                raise MXNetError(
                    f"init_params: provided params missing {missing} "
                    f"(pass allow_missing=True to random-init them)")
        if not allow_extra:
            extra = sorted(set(arg_params or {}) - wanted) + \
                sorted(set(aux_params or {}) - wanted_aux)
            if extra:
                raise MXNetError(
                    f"init_params: provided params contain unknown names "
                    f"{extra} (pass allow_extra=True to ignore them)")
        for m in self._modules:
            m.init_params(initializer=initializer, arg_params=arg_params,
                          aux_params=aux_params,
                          allow_missing=True,
                          force_init=force_init, allow_extra=True)

        # parameter names must not collide across sub-modules (reference:
        # sequential_module.py _check_name)
        seen = {}
        for i, m in enumerate(self._modules):
            for name in m.get_params()[0]:
                if name in seen:
                    raise MXNetError(
                        f"duplicate parameter {name!r} in sub-modules "
                        f"{seen[name]} and {i}")
                seen[name] = i
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req='write'):
        if self.binded and not force_rebind:
            self.logger.warning('Already bound, ignoring bind()')
            return
        if not self._modules:
            raise MXNetError("SequentialModule has no sub-modules; "
                             "call add() first")
        assert shared_module is None, \
            "shared_module is not supported for SequentialModule"
        self._label_shapes = label_shapes
        my_data_shapes = data_shapes
        anybody_ever_needs_label = False
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            meta_take_labels = meta.get(self.META_TAKE_LABELS, False)
            if meta_take_labels:
                module.bind(my_data_shapes, label_shapes,
                            for_training=for_training,
                            inputs_need_grad=(inputs_need_grad or i > 0),
                            force_rebind=force_rebind, grad_req=grad_req)
                anybody_ever_needs_label = True
            else:
                module.bind(my_data_shapes, None,
                            for_training=for_training,
                            inputs_need_grad=(inputs_need_grad or i > 0),
                            force_rebind=force_rebind, grad_req=grad_req)
            if i + 1 < len(self._modules):
                # next module's data = this module's outputs (auto wiring)
                from ..io import DataDesc
                out_shapes = [tuple(o[1]) if isinstance(o, (tuple, list))
                              else tuple(o.shape)
                              for o in module.output_shapes]
                nxt_names = self._modules[i + 1].data_names
                my_data_shapes = [DataDesc(n, s)
                                  for n, s in zip(nxt_names, out_shapes)]
        if not anybody_ever_needs_label:
            self._label_shapes = None
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        for m in self._modules:
            m.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                             optimizer_params=optimizer_params,
                             force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        from ..io import DataBatch
        batch = data_batch
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i + 1 == len(self._modules):
                break
            out = module.get_outputs()
            batch = DataBatch(data=out, label=data_batch.label,
                              pad=getattr(data_batch, 'pad', 0))

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        for m in self._modules:
            m.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        for module, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for m in self._modules:
            m.install_monitor(mon)
