"""PythonModule / PythonLossModule: user-defined module logic in Python.

Reference: python/mxnet/module/python_module.py — modules whose
forward/backward are arbitrary Python (typically numpy) instead of a bound
symbol.  The reference uses these to splice non-differentiable logic or
custom losses into a SequentialModule chain; parameters are empty and
updates are no-ops unless subclassed.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from ..io import DataDesc
from ..ndarray import NDArray
from ..ndarray.ndarray import array as nd_array
from .base_module import BaseModule


class PythonModule(BaseModule):
    """reference: python_module.py PythonModule — parameter-free module
    computing outputs from inputs in Python."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- params: none by default -------------------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        if self._label_shapes is not None:
            # same sync-free contract as Module.update_metric: device-
            # resident accumulation when the metric supports it, so a
            # PythonModule-driven fit/score loop keeps callbacks as its
            # only host sync points too
            eval_metric.accumulate_dict(
                dict(zip(self._label_names, labels or [])),
                dict(zip(self._output_names, self.get_outputs())))

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req='write'):
        if self.binded and not force_rebind:
            self.logger.warning('Already bound, ignoring bind()')
            return
        self._data_shapes = [d if isinstance(d, DataDesc)
                             else DataDesc(*d) for d in data_shapes]
        self._label_shapes = ([l if isinstance(l, DataDesc)
                               else DataDesc(*l) for l in label_shapes]
                              if label_shapes else None)
        self._output_shapes = self._compute_output_shapes()
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    def _compute_output_shapes(self):
        """Subclasses define the output shapes (reference requires
        override)."""
        raise NotImplementedError()

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        self.optimizer_initialized = True


class PythonLossModule(PythonModule):
    """reference: python_module.py PythonLossModule — a pass-through loss
    whose gradient is supplied by ``grad_func`` (default: identity on the
    forward input minus nothing, i.e. user-provided)."""

    def __init__(self, name='pyloss', data_names=('data',),
                 label_names=('softmax_label',), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names,
                         [name + '_output'], logger=logger)
        self._name = name
        assert len(self._data_names) == 1
        if grad_func is not None and not callable(grad_func):
            raise MXNetError("grad_func must be callable")
        self._grad_func = grad_func
        self._scores = None
        self._labels = None
        self._scores_grad = None

    def _compute_output_shapes(self):
        return [(self._name + '_output', self._data_shapes[0].shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, \
            'For a loss module, out_grads should be None'
        assert self.for_training
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, NDArray):
                # analysis: allow(host-sync): PythonLossModule is the reference's HOST-SIDE compat shim — user grad_func returns host values; per-batch crossing is its documented cost
                grad = nd_array(np.asarray(grad))
            self._scores_grad = grad
        else:
            raise MXNetError("PythonLossModule: provide grad_func to "
                             "compute the loss gradient")

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
