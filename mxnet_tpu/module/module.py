"""Module: symbol + executor + optimizer, the intermediate-level trainer
(reference: python/mxnet/module/module.py).

TPU-first design: the reference's DataParallelExecutorGroup (one executor
per GPU, batch split host-side, kvstore reduce — executor_group.py:99,233)
is replaced by ONE executor whose arrays may be sharded over a device mesh
(data-parallel = batch-axis sharding; see mxnet_tpu.parallel).  ``update``
runs a FUSED training step: forward + backward + optimizer update compile
into a single XLA program (the reference needed three engine passes plus a
kvstore round trip per step).
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError, env
from ..context import Context, cpu, current_context
from ..executor import Executor
from ..initializer import Uniform, InitDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     save_checkpoint)
from ..ndarray import NDArray
from .. import optimizer as opt_mod
from .. import random as _rnd
from .base_module import BaseModule, _check_input_names, _parse_data_desc


class Module(BaseModule):
    """reference: module.py:39 Module."""

    def __init__(self, symbol, data_names=('data',),
                 label_names=('softmax_label',), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, mesh=None, sharding_rules=None,
                 compute_dtype=None, zero_stage=None):
        super().__init__(logger=logger)
        if context is None:
            context = [current_context()]
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list
        # -- mesh parallelism (mxnet_tpu.parallel) -------------------------
        # The reference replicated one executor per context and split the
        # batch host-side (executor_group.py:99,233).  Here a context list
        # becomes a dp mesh over those devices, and an explicit `mesh`
        # (or an ambient parallel.use_mesh scope) enables arbitrary
        # dp/tp/pp/sp/ep layouts on the SAME Module code path.
        from .. import parallel as _par
        if mesh is None:
            mesh = _par.current_mesh()
        if mesh is None and len(context) > 1:
            mesh = _par.make_mesh(
                dp=len(context),
                devices=[c.jax_device() for c in context])
        self._mesh = mesh
        self._sharding_rules = sharding_rules
        # Mixed precision: master weights stay fp32; the executor casts
        # per-op inputs to this dtype (see executor.AMP_FP32_OPS).  The
        # TPU-native analog of the reference's --dtype float16 training
        # recipe (example/image-classification/common/fit.py).
        self._compute_dtype = compute_dtype
        # ZeRO-1 optimizer-state sharding over the dp axis.  The modern
        # answer to the reference's update-on-kvstore mode (SURVEY §2.5
        # "gradient aggregation modes" → optimizer-state sharding
        # decision): instead of an optimizer living in a parameter
        # server, each dp rank owns a 1/dp shard of every optimizer
        # state (and fp32 master weight); GSPMD then materializes the
        # reduce-scatter(grads) → sharded update → all-gather(params)
        # schedule inside the one fused step.  Opt-in: zero_stage=1 or
        # MXNET_ZERO_STAGE=1.
        explicit_zero = zero_stage is not None
        if zero_stage is None:
            zero_stage = env("MXNET_ZERO_STAGE", 0)
        if zero_stage not in (0, 1):
            raise ValueError("zero_stage must be 0 or 1 (ZeRO-2/3 shard "
                             "gradients/params too — not implemented; "
                             "ZeRO-1 covers the optimizer-state memory, "
                             "which dominates for Adam-family training)")
        if explicit_zero and zero_stage >= 1 and mesh is None:
            raise MXNetError(
                "zero_stage=1 needs a device mesh with dp>1 — pass "
                "mesh= (parallel.make_mesh) or enter a use_mesh scope")
        if not explicit_zero and zero_stage >= 1:
            from .. import parallel as _par
            dp = (_par.mesh_shape(mesh).get("dp", 1)
                  if mesh is not None else 1)
            if dp <= 1:
                # env-enabled ZeRO silently no-ops without a dp>1 mesh —
                # the user who exported MXNET_ZERO_STAGE=1 must learn the
                # states are replicated, not sharded (the explicit-kwarg
                # path raises instead)
                logging.warning(
                    "MXNET_ZERO_STAGE=1 ignored: no device mesh with "
                    "dp>1 on this Module — optimizer states will be "
                    "fully replicated")
        self._zero_stage = int(zero_stage)

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = list(fixed_param_names) \
            if fixed_param_names is not None else []
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._grad_req = None
        self._exec: Optional[Executor] = None
        self._fused_step = None
        self._run_steps_cache: Dict[tuple, object] = {}
        self._opt_states: Dict[str, tuple] = {}
        self._pending_backward = False

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """reference: module.py load."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = '%s-%04d.states' % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """reference: module.py save_checkpoint."""
        self._symbol.save('%s-symbol.json' % prefix)
        param_name = '%s-%04d.params' % (prefix, epoch)
        self.save_params(param_name)
        logging.info('Saved checkpoint to "%s"', param_name)
        if save_optimizer_states:
            state_name = '%s-%04d.states' % (prefix, epoch)
            self.save_optimizer_states(state_name)
            logging.info('Saved optimizer state to "%s"', state_name)

    # -- properties -----------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [(n, o.shape) for n, o in
                zip(self._output_names, self._exec.outputs)]

    # -- params ---------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        return ({n: self._exec.arg_dict[n] for n in self._param_names},
                dict(self._exec.aux_dict))

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """reference: module.py:460 init_params."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, 'call bind before initializing the parameters'

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    arr._set_data(cache_arr._data)
            else:
                if not allow_missing and cache is not None:
                    raise RuntimeError(f"{name} is not presented")
                if initializer is not None:
                    init = initializer
                    attrs = self._symbol.attr_dict()
                    if name in attrs and '__init__' in attrs[name]:
                        from .. import initializer as init_mod
                        import json as _json
                        klass, kw = _json.loads(attrs[name]['__init__'])
                        init = init_mod.create(klass, **kw)
                    # global_init lets composite inits (FusedRNN) fall
                    # back to the caller's initializer per weight piece
                    init(InitDesc(name, global_init=initializer), arr)

        cache_arg = arg_params if arg_params is not None else \
            (self._arg_params if self._arg_params else None)
        cache_aux = aux_params if aux_params is not None else \
            (self._aux_params if self._aux_params else None)
        if not allow_extra:
            # the reference rejects unknown names unless allow_extra=True
            # (module.py set_params) — silently dropping a typo'd weight
            # is how a checkpoint loads "successfully" untrained.  Every
            # symbol argument (params, inputs, labels, STATES) is known.
            known = set(self._symbol.list_arguments()) \
                | set(self._aux_names)
            for cache in (cache_arg, cache_aux):
                unknown = [n for n in (cache or {}) if n not in known]
                if unknown:
                    raise ValueError(
                        "extra parameters not in the symbol (pass "
                        "allow_extra=True to ignore): %r" % sorted(unknown))
        for name in self._param_names:
            _impl(name, self._exec.arg_dict[name], cache_arg)
        for name in self._aux_names:
            _impl(name, self._exec.aux_dict[name], cache_aux)
        self.params_initialized = True
        self._params_dirty = False

    # -- bind -----------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req='write'):
        """reference: module.py bind → DataParallelExecutorGroup; here: one
        simple_bind'ed jit executor (sharding covers multi-device)."""
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning('Already bound, ignoring bind()')
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes, self._label_shapes = _parse_data_desc(
            self.data_names, self.label_names, data_shapes, label_shapes)

        shapes = {d.name: d.shape for d in self._data_shapes}
        type_dict = {d.name: getattr(d, 'dtype', np.float32)
                     for d in self._data_shapes}
        if self._label_shapes:
            shapes.update({l.name: l.shape for l in self._label_shapes})
            type_dict.update({l.name: getattr(l, 'dtype', np.float32)
                              for l in self._label_shapes})

        req = {}
        for name in self._symbol.list_arguments():
            if name in self._data_names:
                req[name] = 'write' if inputs_need_grad else 'null'
            elif name in self._label_names or name in self._state_names:
                req[name] = 'null'
            elif name in self._fixed_param_names:
                req[name] = 'null'
            else:
                req[name] = grad_req if for_training else 'null'
        self._grad_req = req

        self._exec = Executor.simple_bind(
            self._symbol, self._context[0], grad_req=req,
            type_dict=type_dict, shapes=shapes,
            compute_dtype=self._compute_dtype)
        self._apply_shardings()
        self._fused_step = None
        self._run_steps_cache = {}
        if self.params_initialized:
            # params loaded before bind (Module.load) — copy into executor
            # (reference: module.py bind → exec_group.set_params)
            if self._arg_params:
                self._exec.copy_params_from(self._arg_params,
                                            self._aux_params,
                                            allow_extra_params=True)
        if shared_module is not None and shared_module.params_initialized:
            arg, aux = shared_module.get_params()
            self._exec.copy_params_from(arg, aux, allow_extra_params=True)
            self.params_initialized = True

    def reshape(self, data_shapes, label_shapes=None):
        """Re-bind for new input shapes, keeping parameters, grad_req and
        optimizer state (reference: module.py:444 Module.reshape —
        batch-size or image-size switch without re-initialization).

        Delegates to Executor.reshape — the same path forward() uses for
        implicit shape changes — which carries params/aux/grad_req/
        shardings over; each shape gets its own jit program and
        re-reshaping to a previous shape reuses XLA's compile cache."""
        assert self.binded and self.params_initialized
        had_labels = bool(self._label_shapes)
        new_data, new_labels = _parse_data_desc(
            self.data_names, self.label_names, data_shapes, label_shapes)
        if had_labels and not new_labels:
            # the executor would keep the label at the OLD batch size and
            # the next training step would fail deep inside the jit
            # (checked BEFORE mutating module metadata, so a caught error
            # leaves the module consistent)
            raise MXNetError(
                "reshape: this module was bound with label_shapes — pass "
                "matching label_shapes (the label batch must move with "
                "the data batch)")
        self._data_shapes, self._label_shapes = new_data, new_labels
        new = {d.name: tuple(d.shape) for d in self._data_shapes}
        if self._label_shapes:
            new.update({l.name: tuple(l.shape)
                        for l in self._label_shapes})
        self._exec = self._exec.reshape(**new)
        self._apply_shardings()
        self._fused_step = None
        self._run_steps_cache = {}

    def _reset_bind(self):
        self.binded = False
        self._exec = None
        self._fused_step = None
        self._run_steps_cache = {}

    def _apply_shardings(self):
        """Annotate the executor's args with mesh shardings: inputs batch-
        sharded over dp, params per the rules (default replicated)."""
        if self._mesh is None or self._exec is None:
            return
        from .. import parallel as _par
        mesh = self._mesh
        dp = _par.mesh_shape(mesh).get("dp", 1)
        pspecs = {}
        io_names = set(self._data_names) | set(self._label_names)
        for n, arr in self._exec.arg_dict.items():
            if n in io_names:
                if dp > 1 and arr.ndim and arr.shape[0] % dp:
                    raise MXNetError(
                        f"batch dim of {n!r} ({arr.shape[0]}) not divisible "
                        f"by dp={dp}; pad the batch (NDArrayIter pads the "
                        f"final partial batch)")
                pspecs[n] = _par.data_pspec(arr.ndim)
            else:
                pspecs[n] = _par.infer_pspec(n, arr.shape, mesh,
                                             self._sharding_rules)
        aux_pspecs = {
            n: _par.infer_pspec(n, a.shape, mesh, self._sharding_rules)
            for n, a in self._exec.aux_dict.items()}
        self._exec.set_shardings(mesh, pspecs, aux_pspecs)

    # -- optimizer ------------------------------------------------------------
    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        """reference: module.py:556 init_optimizer."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning('optimizer already initialized, ignoring...')
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        arg_params = {n: self._exec.arg_dict[n] for n in self._param_names}
        (kvstore_obj, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), arg_params)
        batch_size = self._data_shapes[0].shape[0]
        if kvstore_obj and 'dist' in kvstore_obj.type:
            batch_size *= kvstore_obj.num_workers
        if isinstance(optimizer, str):
            idx2name = {n: n for n in self._param_names}
            optimizer_params = dict(optimizer_params)
            if 'rescale_grad' not in optimizer_params:
                # reference: module.py:486 — grads are per-batch sums
                optimizer_params['rescale_grad'] = 1.0 / batch_size
            optimizer = opt_mod.create(
                optimizer, sym=self.symbol, param_idx2name=idx2name,
                **optimizer_params)
        else:
            assert isinstance(optimizer, opt_mod.Optimizer)

        self._optimizer = optimizer
        self._kvstore = kvstore_obj
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore_obj:
            # copy initialized params into the store
            _initialize_kvstore(kvstore=kvstore_obj,
                                param_arrays=[[arg_params[n]] for n in
                                              self._param_names],
                                arg_params=arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore:
            kvstore_obj.set_optimizer(self._optimizer)
        else:
            self._updater = opt_mod.get_updater(optimizer)

        # per-param optimizer state for the fused step (multi-precision
        # prepends an fp32 master copy for fp16/bf16 weights — reference:
        # optimizer.py Updater master-weight cast)
        self._opt_states = {
            n: optimizer.create_state_multi_precision(
                n, self._exec.arg_dict[n])
            for n in self._update_names()}
        self._shard_opt_states()

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def _update_names(self):
        return [n for n in self._param_names
                if self._grad_req.get(n, 'null') != 'null']

    def _zero_pspec(self, arr):
        """ZeRO-1 partition spec (delegates to the shared rule in
        parallel.sharding so Module and Trainer cannot diverge)."""
        from .. import parallel as _par
        return _par.zero_pspec(arr, self._zero_dp())

    def _zero_dp(self):
        from .. import parallel as _par
        if self._mesh is None:
            return 1
        return _par.mesh_shape(self._mesh).get("dp", 1)

    def _shard_opt_states(self):
        """Place every optimizer-state array (incl. fp32 master weights)
        with its ZeRO-1 sharding.  Placement here + GSPMD propagation in
        the fused jit is the whole mechanism — no collective is written
        by hand; XLA inserts reduce-scatter/all-gather over ICI."""
        if self._zero_stage < 1 or self._zero_dp() <= 1:
            return
        import jax
        from jax.sharding import NamedSharding
        mesh = self._mesh
        for n, states in self._opt_states.items():
            for s in states:
                if s is None:   # e.g. DCASGD momentum=0 slot
                    continue
                s._set_data(jax.device_put(
                    s._data, NamedSharding(mesh, self._zero_pspec(s))))

    # -- compute --------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        kwargs = {}
        for name, arr in zip(self._data_names, data_batch.data):
            kwargs[name] = arr
        if data_batch.label is not None and self._label_names:
            for name, arr in zip(self._label_names, data_batch.label):
                kwargs[name] = arr
        # shape change (e.g. final partial batch with pad) → jit recompiles;
        # data AND label shapes must move together (reference: module.py
        # reshape(data_shapes, label_shapes))
        io_names = self._data_names + self._label_names
        cur = {n: tuple(self._exec.arg_dict[n].shape)
               for n in io_names if n in self._exec.arg_dict}
        new = {n: tuple(kwargs[n].shape) for n in io_names if n in kwargs}
        if any(cur.get(n) != s for n, s in new.items()):
            self._exec = self._exec.reshape(**new)
            self._apply_shardings()
            self._fused_step = None
            self._run_steps_cache = {}
        self._exec.forward(is_train=is_train, **kwargs)
        self._pending_backward = False
        self._out_grads = None

    def backward(self, out_grads=None):
        """Mark backward pending; gradients materialize lazily (or fuse into
        update())."""
        assert self.binded and self.params_initialized
        self._pending_backward = True
        self._out_grads = out_grads
        exec_ = self._exec
        for name, garr in exec_.grad_dict.items():
            if garr is not None:
                garr._set_lazy(
                    lambda og=out_grads: exec_.backward(out_grads=og))

    def update(self):
        """One fused XLA program: forward + backward + optimizer update
        (reference: module.py:615 update → kvstore push/pull + updater)."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        opt = self._optimizer
        names = self._update_names()
        use_fused = (env("MXNET_EXEC_BULK_EXEC_TRAIN", True)
                     and getattr(opt, "pure_update", False)
                     and not self._update_on_kvstore
                     and getattr(self, '_out_grads', None) is None)
        if not use_fused:
            self._exec.backward(out_grads=getattr(self, '_out_grads', None))
            if self._update_on_kvstore:
                _update_params_on_kvstore(
                    [[self._exec.arg_dict[n]] for n in names],
                    [[self._exec.grad_dict[n]] for n in names],
                    self._kvstore, names)
            else:
                _update_params(
                    [self._exec.arg_dict[n] for n in names],
                    [self._exec.grad_dict[n] for n in names],
                    updater=self._updater, num_device=1,
                    kvstore=self._kvstore, param_names=names)
            self._pending_backward = False
            return

        sig = opt.hyperparam_signature()
        if self._fused_step is None or \
                getattr(self, "_fused_hparam_sig", None) != sig:
            # hyperparameters (momentum, betas, rescale_grad...) are baked
            # into the trace — rebuild if they were mutated mid-run
            self._fused_step = self._build_fused_step(names)
            self._fused_hparam_sig = sig
        for n in names:
            opt._update_count(n)
        t = opt._index_update_count[names[0]] if names else 1
        lrs = tuple(np.float32(opt._get_lr(n)) for n in names)
        wds = tuple(np.float32(opt._get_wd(n)) for n in names)
        # cache lr/wd device buffers while unchanged: per-step host→device
        # scalar transfers (2 per param) would dominate step latency on a
        # remote-attached chip
        cache = getattr(self, "_lrwd_cache", None)
        if cache is not None and cache[0] == (lrs, wds):
            lrs, wds = cache[1]
        else:
            key_ = (lrs, wds)
            lrs = tuple(jnp.asarray(v) for v in lrs)
            wds = tuple(jnp.asarray(v) for v in wds)
            self._lrwd_cache = (key_, (lrs, wds))
        snapshot = self._exec._snapshot
        if snapshot is None:
            raise MXNetError("update() called before forward()")
        arg_vals, aux_vals, key, _ = snapshot
        pvals = tuple(arg_vals[i] for i in self._fused_upd_idx)
        io_vals = tuple(arg_vals[i] for i in self._fused_io_idx)
        states = tuple(tuple(s._data for s in self._opt_states[n])
                       for n in names)
        # t is only read by needs_t optimizers (Adam bias correction);
        # otherwise reuse one cached device scalar instead of a per-step
        # host→device transfer
        if getattr(opt, "needs_t", False):
            t_dev = jnp.asarray(t, jnp.int32)
        else:
            t_dev = getattr(self, "_t_const", None)
            if t_dev is None:
                t_dev = self._t_const = jnp.asarray(0, jnp.int32)
        from .. import profiler as _prof
        _prof.record_dispatch("fused_step.dispatch")
        with _prof.scope("fused_train_step", "symbolic"):
            outs, new_aux, new_params, new_states = self._fused_step(
                pvals, io_vals, aux_vals, key, states, lrs, wds, t_dev)
        exec_ = self._exec
        if exec_._out_arrays is not None:
            for oa, v in zip(exec_._out_arrays, outs):
                oa._set_data(v)
        for a, v in zip(exec_.aux_arrays, new_aux):
            a._set_data(v)
        for n, w in zip(names, new_params):
            exec_.arg_dict[n]._set_data(w)
        for n, st in zip(names, new_states):
            for s, v in zip(self._opt_states[n], st):
                s._set_data(v)
        if self._fused_donate:
            self._poison_after_donate()
        self._pending_backward = False

    def _poison_after_donate(self):
        """A donated step consumed the old param/aux/state buffers; the
        pre-step snapshots and any lazy thunks referencing them
        (gradients, outputs from earlier forwards) are no longer
        executable — poison them with a clear error."""
        from ..executor import poison_stale
        exec_ = self._exec
        exec_._snapshot = None
        for name, garr in exec_.grad_dict.items():
            if garr is not None and garr._thunk is not None:
                poison_stale(garr, "gradient")
        for ref in exec_._issued_outs:
            oarr = ref()
            if oarr is not None and oarr._thunk is not None:
                poison_stale(oarr, "output")
        exec_._issued_outs = []

    def _split_arg_idx(self, names):
        """Partition executor arg positions into (updated params, io) —
        the ONE source of truth for the index layout shared by the step
        body (_make_step_body) and the scan driver's io scatter
        (_run_steps_fused)."""
        arg_names = self._exec._arg_names
        upd_idx = [arg_names.index(n) for n in names]
        upd_set = set(upd_idx)
        io_idx = [i for i in range(len(arg_names)) if i not in upd_set]
        return upd_idx, io_idx

    def _make_step_body(self, names, with_grads=False):
        """Build the PURE single fused-step function
        ``step(pvals, io_vals, aux_vals, key, states, lrs, wds, t) ->
        (outs, new_aux, new_params, new_states)`` shared by the per-step
        jit (update) and the K-step scan (run_steps): both drivers trace
        the SAME body, so scanned training is bit-equivalent to eager
        fused steps by construction.

        ``with_grads`` appends the raw (pre-rescale) per-param gradients
        to the return — the fused-dist driver ships exactly these over
        the kvstore wire, the same quantity the eager dist loop reads
        from grad_dict, while the LOCAL update the body already applied
        keeps the in-chunk weight trajectory fresh (the worker-side
        replica of the server's update; docs/PERF_NOTES.md round 10)."""
        exec_ = self._exec
        run = exec_._run
        arg_names = exec_._arg_names
        upd_idx, io_idx = self._split_arg_idx(names)
        self._fused_upd_idx = upd_idx
        self._fused_io_idx = io_idx
        opt = self._optimizer
        needs_t = getattr(opt, "needs_t", False)
        # static per-param decision: multi-precision iff a master fp32 copy
        # was prepended by create_state_multi_precision
        use_mp = [opt.mp_states_active(exec_.arg_dict[n],
                                       self._opt_states[n])
                  for n in names]

        from ..executor import maybe_mirror
        run_fwd = maybe_mirror(run)
        zero1 = self._zero_stage >= 1 and self._zero_dp() > 1
        constrain = self._mesh is not None
        if constrain:
            from .. import parallel as _par
            # params leave the step in their RULE sharding (tp weights
            # stay tp-sharded; replicated params replicated) — an
            # unconditional P() here would all-gather tensor-parallel
            # weights onto every chip.  Pinning is REQUIRED on any mesh,
            # not just under ZeRO: free GSPMD propagation may emit a
            # param with a different sharding than the next forward's
            # declared in_sharding, and on a process-spanning mesh the
            # executor cannot fall back to a host round-trip to fix it.
            param_pspecs = [
                _par.infer_pspec(n, self._exec.arg_dict[n].shape,
                                 self._mesh, self._sharding_rules)
                for n in names]

        def step(pvals, io_vals, aux_vals, key, states, lrs, wds, t):
            def f(pv):
                av = [None] * len(arg_names)
                for i, v in zip(upd_idx, pv):
                    av[i] = v
                for i, v in zip(io_idx, io_vals):
                    av[i] = v
                outs, new_aux = run_fwd(tuple(av), aux_vals, key, True)
                diff = tuple(o for o in outs
                             if jnp.issubdtype(o.dtype, jnp.inexact))
                return diff, (outs, new_aux)

            diff, vjp_fn, (outs, new_aux) = jax.vjp(f, pvals, has_aux=True)
            cts = tuple(jnp.ones(o.shape, o.dtype) for o in diff)
            grads = vjp_fn(cts)[0]
            # per-param dispatch shared with Trainer (optimizer.apply_fused
            # owns the multi-precision contract)
            new_params, new_states = opt.apply_fused(
                pvals, grads, states, lrs, wds, use_mp,
                ts=(t,) * len(names) if needs_t else None)
            if constrain:
                # pin the schedule: params leave the step in their rule
                # sharding (under ZeRO-1 the dp all-gather happens HERE,
                # inside the fused program, overlapped by XLA)
                from jax.sharding import NamedSharding
                mesh_ = self._mesh
                new_params = tuple(
                    jax.lax.with_sharding_constraint(
                        w, NamedSharding(mesh_, ps))
                    for w, ps in zip(new_params, param_pspecs))
            if zero1:
                # state math stays dp-sharded (GSPMD reduce-scatters the
                # grads feeding it)
                new_states = _par.constrain_zero_states(
                    new_states, self._mesh, self._zero_dp())
            if with_grads:
                return (outs, new_aux, tuple(new_params),
                        tuple(new_states), tuple(grads))
            return outs, new_aux, tuple(new_params), tuple(new_states)

        return step

    def _build_fused_step(self, names):
        # Donate the buffers the step replaces — params, aux (BN stats),
        # optimizer state — so XLA updates them in place in HBM (the analog
        # of the reference's in-place engine writes; halves peak param
        # memory and removes copy traffic).
        self._fused_donate = bool(env("MXNET_FUSED_DONATE", True))
        donate = (0, 2, 4) if self._fused_donate else ()
        return jax.jit(self._make_step_body(names), donate_argnums=donate)

    # -- multi-step driver --------------------------------------------------
    def run_steps(self, data, label=None, k=None, eval_metric=None):
        """Run K fused training steps as ONE XLA program (`jax.lax.scan`
        over the fused fwd+bwd+update body): one host dispatch launches
        all K steps, amortizing the per-dispatch host cost (~12 ms
        through a remote-attached chip, docs/PERF_NOTES.md) to 1/K per
        step — the whole-program TPU execution move of Fischer & Saba
        (arXiv:1810.09868), and the engine-level overlap idea of MXNet
        taken to its limit: the host leaves the training loop entirely.

        ``data``/``label`` carry the K batches stacked on a leading step
        axis (array ``(k, batch, ...)``, dict name->array, or a list of
        per-step batches for a single input).  Parameters, aux states
        (BatchNorm statistics) and optimizer state flow step-to-step in
        the scan carry, with their buffers donated (in-place HBM
        updates); per-step lr/wd schedules and update counts are
        precomputed host-side so schedules advance exactly as K eager
        ``update()`` calls would.  Host-visible values (the per-step
        outputs — loss heads included) accumulate as stacked scan
        outputs and are read back ONCE per call: pass ``eval_metric`` to
        fold them into a metric here (single readback), or read the
        returned stacked outputs yourself.

        The compiled program is cached per (K, shapes, param set,
        optimizer hyperparameters).  dist_async update-on-kvstore runs
        the CHUNKED variant of the same program — one dispatch per
        ``MXNET_KVSTORE_FUSED_CHUNK`` steps with the grad-push/weight-
        pull wire overlapped behind the next chunk's compute
        (:meth:`_run_steps_fused_dist`).  Falls back to the eager
        per-step driver (BaseModule.run_steps) for K=1, shape changes
        vs the bound shapes (bucketing / variable shapes), non-pure
        optimizers, non-dist_async update-on-kvstore,
        ``MXNET_KVSTORE_FUSED=0``, and
        ``MXNET_EXEC_BULK_EXEC_TRAIN=0`` — same math, K dispatches.

        Returns the per-step outputs stacked on a leading K axis, one
        NDArray per output; scanned training is bit-equivalent to K
        eager fused steps because both trace the SAME step body
        (tests/test_run_steps.py pins this).
        """
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        from .base_module import _canon_step_inputs
        data_arrays, k = _canon_step_inputs(
            self._data_names, data, "data", k)
        label_arrays, k = _canon_step_inputs(
            self._label_names, label, "label", k)
        opt = self._optimizer
        names = self._update_names()
        shapes_ok = all(
            tuple(a.shape[1:]) == tuple(self._exec.arg_dict[n].shape)
            for n, a in zip(self._data_names + self._label_names,
                            data_arrays + label_arrays))
        fusable = (k > 1 and bool(names) and shapes_ok
                   and env("MXNET_EXEC_BULK_EXEC_TRAIN", True)
                   and getattr(opt, "pure_update", False))
        if self._update_on_kvstore:
            # dist_async update-on-kvstore no longer falls back to eager:
            # the chunked driver scans fwd+bwd+local-update per chunk and
            # overlaps the push/pull wire behind the next chunk's compute
            # (_run_steps_fused_dist).  Other update-on-kvstore stores
            # (local multi-device, dist_sync) keep the eager per-step
            # loop — they have no async wire to overlap.  Elastic jobs
            # ride the chunked driver too: an in-flight pull_async
            # handle now REPLANS itself against the post-bump stripe
            # layout from inside wait() (kvstore._PullHandle._replan;
            # docs/ROBUSTNESS.md replan contract), and the push leg
            # already repaired+rerouted.
            if (fusable and self._kvstore is not None
                    and getattr(self._kvstore, "type", "") == "dist_async"
                    and env("MXNET_KVSTORE_FUSED", True)):
                return self._run_steps_fused_dist(
                    data_arrays, label_arrays, k, names, eval_metric)
            return self._run_steps_eager(data_arrays, label_arrays, k,
                                         eval_metric)
        if not fusable:
            return self._run_steps_eager(data_arrays, label_arrays, k,
                                         eval_metric)
        return self._run_steps_fused(data_arrays, label_arrays, k, names,
                                     eval_metric)

    def _compile_run_steps_scan(self, names, eval_metric, use_dev_metric,
                                donate, with_grads=False):
        """Compiled K-step scan program over the fused step body, cached
        per (param set, optimizer hyperparameters, donation, metric
        device signature, grads-on-the-wire) — shared by the local
        fused driver (:meth:`_run_steps_fused`) and the dist_async
        chunked driver (:meth:`_run_steps_fused_dist`), which
        additionally scans the per-step raw gradients out for the
        kvstore wire.  Returns
        ``(fn, upd_idx, io_idx, step_pos, const_pos)``."""
        exec_ = self._exec
        arg_names = exec_._arg_names
        upd_idx, io_idx = self._split_arg_idx(names)
        step_names = set(self._data_names) | set(self._label_names)
        step_pos = [j for j, i in enumerate(io_idx)
                    if arg_names[i] in step_names]
        const_pos = [j for j, i in enumerate(io_idx)
                     if arg_names[i] not in step_names]
        cache = self._run_steps_cache
        cache_key = (tuple(names), self._optimizer.hyperparam_signature(),
                     donate, with_grads,
                     eval_metric._device_sig() if use_dev_metric else None)
        from ..executor import scan_cache_lookup, scan_cache_store
        fn = scan_cache_lookup(cache, cache_key)
        if fn is None:
            from ..executor import build_multi_step
            body = self._make_step_body(names, with_grads=with_grads)
            metric = eval_metric if use_dev_metric else None
            out_names = self._output_names
            # label name -> stacked-input slot, in LABEL_NAMES order:
            # the metric fold must see labels exactly as update_metric
            # presents them (dict insertion order feeds _select_dict)
            step_arg_names = [arg_names[io_idx[j]] for j in step_pos]
            label_slots = [(nm, step_arg_names.index(nm))
                           for nm in self._label_names
                           if nm in step_arg_names]

            def scan_body(carry, x, const):
                pvals, aux_vals, states, mstate = carry
                step_io, key, lrs, wds, t = x
                io_vals = [None] * len(io_idx)
                for j, v in zip(step_pos, step_io):
                    io_vals[j] = v
                for j, v in zip(const_pos, const):
                    io_vals[j] = v
                res = body(pvals, tuple(io_vals), aux_vals, key, states,
                           lrs, wds, t)
                outs, new_aux, new_params, new_states = res[:4]
                if metric is not None:
                    mstate = metric.device_update_dict(
                        mstate,
                        {nm: step_io[i] for nm, i in label_slots},
                        dict(zip(out_names, outs)))
                ys = (outs, res[4]) if with_grads else outs
                return (new_params, new_aux, new_states, mstate), ys

            fn = scan_cache_store(cache, cache_key,
                                  build_multi_step(scan_body,
                                                   donate=donate))
        return fn, upd_idx, io_idx, step_pos, const_pos

    def _run_steps_fused(self, data_arrays, label_arrays, k, names,
                         eval_metric):
        exec_ = self._exec
        opt = self._optimizer
        arg_names = exec_._arg_names
        donate = bool(env("MXNET_FUSED_DONATE", True))
        # metric accumulation rides the scan carry when the metric has a
        # device form: K steps of metrics cost ZERO extra dispatches and
        # ZERO readbacks — the state stays on device until a callback
        # syncs it (the tentpole of the sync-free loop; metrics without
        # a device form keep the old one-readback host fold below)
        use_dev_metric = (eval_metric is not None
                          and getattr(eval_metric, "device_enabled",
                                      lambda: False)())
        fn, upd_idx, io_idx, step_pos, const_pos = \
            self._compile_run_steps_scan(names, eval_metric,
                                         use_dev_metric, donate)
        self._fused_upd_idx = upd_idx
        self._fused_io_idx = io_idx
        self._fused_donate = donate

        # per-step lr/wd/t precomputed host-side (shared helper with
        # Trainer.step_k): schedules advance exactly as K eager update()
        # calls would, then travel as (k,)-arrays scanned with the data,
        # so mid-scan lr changes cost nothing.  The step body takes ONE
        # t per step (all names update together), so ts uses column 0.
        # schedule_rollback keeps the host schedule state transactional
        # with the dispatch: a failed compile/launch must not leave
        # counts K steps ahead of the params.
        from ..executor import precompute_step_schedules, schedule_rollback
        from .. import profiler as _prof
        with schedule_rollback(opt):
            lrs, wds, tcols = precompute_step_schedules(opt, names, k)
            ts = tcols[0]

            # per-step RNG keys consume the global counter exactly like
            # K eager forwards; RNG-free programs share one constant key
            # (same discipline as random.key_for)
            run = exec_._run
            if getattr(run, "needs_rng", False):
                keys = jnp.stack([_rnd.next_key() for _ in range(k)])
            else:
                keys = jnp.stack([_rnd.key_for(run)] * k)

            arg_vals = exec_._arg_vals()
            aux_vals = exec_._aux_vals()
            pvals = tuple(arg_vals[i] for i in upd_idx)
            const = tuple(arg_vals[io_idx[j]] for j in const_pos)
            step_io = tuple(self._stacked_input(arg_names[io_idx[j]],
                                                data_arrays, label_arrays)
                            for j in step_pos)
            states = tuple(tuple(s._data for s in self._opt_states[n])
                           for n in names)
            # seed the metric carry from any pending device state, so a
            # log interval spanning eager batches AND run_steps calls
            # accumulates continuously.  _take (not peek): the carry is
            # DONATED — detaching first means a failed dispatch leaves
            # the metric empty, not pointing at deleted buffers
            init_m = eval_metric._take_device_state() \
                if use_dev_metric else ()

            _prof.record_dispatch("run_steps.dispatch")
            with _prof.scope("run_steps_scan", "symbolic"):
                (new_pvals, new_aux, new_states, new_m), ys = fn(
                    (pvals, aux_vals, states, init_m),
                    (step_io, keys, lrs, wds, ts), const)
        self._params_dirty = True
        for n, w in zip(names, new_pvals):
            exec_.arg_dict[n]._set_data(w)
        for a, v in zip(exec_.aux_arrays, new_aux):
            a._set_data(v)
        for n, st in zip(names, new_states):
            for s, v in zip(self._opt_states[n], st):
                s._set_data(v)
        if donate:
            self._poison_after_donate()
        self._pending_backward = False

        # expose the LAST step's outputs through get_outputs() (lazy: the
        # slice dispatches only if actually read)
        from ..executor import make_lazy_outputs

        def last_thunk(outs):
            def thunk():
                for oa, y in zip(outs, ys):
                    oa._set_data(y[-1])
            return thunk

        exec_._out_arrays = make_lazy_outputs(
            exec_._out_aval_list(True), last_thunk)

        stacked = [NDArray(y) for y in ys]
        if use_dev_metric:
            # K steps of metrics came back as the scan carry — adopt it
            # as the metric's pending state; a later sync() (callback /
            # get_name_value) is the only readback
            eval_metric._absorb_device_state(new_m)
        elif eval_metric is not None:
            self._fold_metric(eval_metric, label_arrays, ys, k)
        return stacked

    def _run_steps_fused_dist(self, data_arrays, label_arrays, k, names,
                              eval_metric):
        """K update-on-kvstore steps as a CHUNKED scan with the wire
        overlapped behind compute — dispatch amortization and the
        pipelined dist_async wire finally compose (the MXNet
        dependency-engine thesis rebuilt on XLA async dispatch;
        docs/PERF_NOTES.md round 10).

        The scanned body is the SAME fused step as the local driver —
        fwd+bwd plus a LOCAL optimizer update (the worker-side replica
        of the server's updater; both run ``Optimizer._update_impl``)
        — so the in-chunk weight trajectory stays fresh, and it
        additionally scans out the raw per-step gradients.  Per chunk
        of ``MXNET_KVSTORE_FUSED_CHUNK`` steps the host reads those
        gradients back in ONE stacked device_get, pushes them per step
        through the pipelined window (small keys coalesce per
        envelope) and enqueues a non-blocking ``pull_async``; the
        round resolves while the NEXT chunk computes
        (executor.drive_chunked_dist), and its server-authoritative
        weights replace the carry exactly
        ``MXNET_KVSTORE_FUSED_STALENESS`` chunk boundaries later.
        Staleness 0 degrades to a barrier'd boundary: single-worker it
        is bit-identical to the eager dist loop (the local replica and
        the server apply identical update sequences); multi-worker the
        contract is the elastic handoff one — bit-identical at
        quiescent sync points for commutative updates, async-SGD-grade
        in between.  Optimizer state and aux (BN stats) stay
        worker-local between sync points; the final pull is adopted as
        the authoritative weights (fp32 masters included for
        multi-precision params), exactly like the eager loop's last
        pull.  Under MXNET_KVSTORE_ELASTIC a roster bump mid-drive is
        survivable: the push leg repairs and re-routes through
        _submit_planned, and an in-flight pull handle replans its
        unserved stripes against the new layout from inside wait()
        (docs/ROBUSTNESS.md replan contract).  Transport kills still
        recover through the window replay underneath; a HARD failure
        mid-drive writes the carry's last chunk-output state back so
        the module stays readable, then raises."""
        exec_ = self._exec
        opt = self._optimizer
        kv = self._kvstore
        arg_names = exec_._arg_names
        donate = bool(env("MXNET_FUSED_DONATE", True))
        use_dev_metric = (eval_metric is not None
                          and getattr(eval_metric, "device_enabled",
                                      lambda: False)())
        fn, upd_idx, io_idx, step_pos, const_pos = \
            self._compile_run_steps_scan(names, eval_metric,
                                         use_dev_metric, donate,
                                         with_grads=True)
        self._fused_upd_idx = upd_idx
        self._fused_io_idx = io_idx
        self._fused_donate = donate

        from ..executor import (drive_chunked_dist, fused_dist_knobs,
                                precompute_step_schedules,
                                schedule_rollback)
        chunk, staleness = fused_dist_knobs(k)
        shapes = {n: tuple(exec_.arg_dict[n].shape) for n in names}
        # multi-precision params update on the fp32 master in states[0]
        # (apply_fused recasts the weight from it), so adopting pulled
        # server weights must ALSO overwrite the master — replacing only
        # pvals would be recomputed away on the very next step
        use_mp = [opt.mp_states_active(exec_.arg_dict[n],
                                       self._opt_states[n])
                  for n in names]
        from .. import profiler as _prof
        with schedule_rollback(opt):
            # worker-side schedules advance per step exactly as the
            # server's per-push counts do (single worker: identical lr
            # sequence; multi-worker the server counts all ranks'
            # pushes — the same server-authoritative behavior the
            # eager dist loop has)
            lrs, wds, tcols = precompute_step_schedules(opt, names, k)
            ts = tcols[0]
            run = exec_._run
            if getattr(run, "needs_rng", False):
                keys = jnp.stack([_rnd.next_key() for _ in range(k)])
            else:
                keys = jnp.stack([_rnd.key_for(run)] * k)
            arg_vals = exec_._arg_vals()
            aux_vals = exec_._aux_vals()
            const = tuple(arg_vals[io_idx[j]] for j in const_pos)
            step_io = tuple(self._stacked_input(arg_names[io_idx[j]],
                                                data_arrays, label_arrays)
                            for j in step_pos)
            init_m = eval_metric._take_device_state() \
                if use_dev_metric else ()
            carry = {
                "pvals": tuple(arg_vals[i] for i in upd_idx),
                "aux": aux_vals,
                "states": tuple(
                    tuple(s._data for s in self._opt_states[n])
                    for n in names),
                "m": init_m,
                "outs": [],
            }

            def adopt(adopted):
                # chunk-boundary re-sync: the carry WEIGHTS adopt the
                # pulled server values (authoritative — they include
                # every worker's pushes through the due chunk); for a
                # multi-precision param the fp32 MASTER in states[0]
                # adopts too (the update runs on it and recasts the
                # weight, so it is the real carrier).  The rest of the
                # optimizer state and aux stay local — the
                # async-SGD-grade part of the contract.
                pvals, states = [], list(carry["states"])
                for i, n in enumerate(names):
                    w = jnp.asarray(adopted[n])
                    if use_mp[i]:
                        master = w.astype(jnp.float32)
                        states[i] = (master,) + tuple(states[i][1:])
                        w = master.astype(exec_.arg_dict[n].dtype)
                    else:
                        w = w.astype(exec_.arg_dict[n].dtype)
                    pvals.append(w)
                carry["pvals"] = tuple(pvals)
                carry["states"] = tuple(states)

            def dispatch_chunk(j, lo, hi, adopted):
                if adopted is not None:
                    adopt(adopted)
                xs = (tuple(a[lo:hi] for a in step_io), keys[lo:hi],
                      tuple(v[lo:hi] for v in lrs),
                      tuple(v[lo:hi] for v in wds), ts[lo:hi])
                _prof.record_dispatch("run_steps.dist_chunk")
                with _prof.scope("run_steps_dist_chunk", "symbolic"):
                    (new_p, new_aux, new_st, new_m), (outs, grads) = fn(
                        (carry["pvals"], carry["aux"], carry["states"],
                         carry["m"]), xs, const)
                carry.update(pvals=new_p, aux=new_aux, states=new_st,
                             m=new_m)
                carry["outs"].append(outs)
                # ONE stacked readback of the chunk's per-step raw
                # gradients — the wire needs host bytes; this blocks on
                # the chunk's COMPUTE only (the wire round itself is
                # what the driver overlaps behind the next chunk)
                grads_np = jax.device_get(grads)
                _prof.record_host_sync("run_steps.dist_grad_readback")
                return grads_np

            def ship_chunk(j, grads_np):
                return kv.ship_chunk_steps(names, grads_np,
                                           [shapes[n] for n in names])

            try:
                final = drive_chunked_dist(k, chunk, staleness,
                                           dispatch_chunk, ship_chunk)
            except BaseException:
                # a wire failure mid-drive lands AFTER earlier chunks
                # donated the original param/aux/state buffers — but the
                # carry holds the latest chunk's OUTPUT arrays (alive):
                # write them back so the module stays readable at the
                # last locally-completed step, and poison the stale lazy
                # handles exactly like the success path does
                self._writeback_dist_carry(names, carry)
                if donate:
                    self._poison_after_donate()
                raise

        self._params_dirty = True
        # the FINAL pull is the sync point: the local params adopt the
        # server-authoritative weights, exactly how the eager dist
        # loop's last per-step pull leaves them (fp32 masters included)
        adopt(final)
        self._writeback_dist_carry(names, carry)
        if donate:
            self._poison_after_donate()
        self._pending_backward = False

        ys = [jnp.concatenate([c[i] for c in carry["outs"]])
              if len(carry["outs"]) > 1 else carry["outs"][0][i]
              for i in range(len(self._output_names))]

        from ..executor import make_lazy_outputs

        def last_thunk(outs):
            def thunk():
                for oa, y in zip(outs, ys):
                    oa._set_data(y[-1])
            return thunk

        exec_._out_arrays = make_lazy_outputs(
            exec_._out_aval_list(True), last_thunk)

        stacked = [NDArray(y) for y in ys]
        if use_dev_metric:
            eval_metric._absorb_device_state(carry["m"])
        elif eval_metric is not None:
            self._fold_metric(eval_metric, label_arrays, ys, k)
        return stacked

    def _writeback_dist_carry(self, names, carry):
        """Write the dist driver's carry (latest chunk-output params,
        aux, optimizer states) back into the executor — the shared tail
        of the success path (after adopting the final pull) and the
        mid-drive failure path (where the carry is the last consistent
        local state the donated originals can be replaced with)."""
        exec_ = self._exec
        for n, w in zip(names, carry["pvals"]):
            exec_.arg_dict[n]._set_data(w)
        for a, v in zip(exec_.aux_arrays, carry["aux"]):
            a._set_data(v)
        for n, st in zip(names, carry["states"]):
            for s_arr, v in zip(self._opt_states[n], st):
                s_arr._set_data(v)

    def _stacked_input(self, name, data_arrays, label_arrays):
        """Device value for one stacked (k, batch, ...) input, with the
        batch axis (axis 1 of the stack) dp-sharded when a mesh is set."""
        io_names = self._data_names + self._label_names
        arr = (data_arrays + label_arrays)[io_names.index(name)]
        if self._mesh is None:
            return jnp.asarray(arr)
        from .. import parallel as _par
        from jax.sharding import NamedSharding, PartitionSpec
        per_step = _par.data_pspec(np.ndim(arr) - 1)
        sh = NamedSharding(self._mesh,
                           PartitionSpec(None, *tuple(per_step)))
        return self._exec._sharded(jnp.asarray(arr), sh)

    def _fold_metric(self, eval_metric, label_arrays, ys, k):
        """Host fallback for metrics without a device form: ONE host
        readback for all K steps' outputs, then fold them into the
        metric per step.  Values are NDArray-wrapped — the classic
        custom-metric contract (user update() may call .asnumpy()), at
        the price of the legacy path's per-value syncs."""
        from .. import profiler as _prof
        host_outs = jax.device_get(ys)
        _prof.record_dispatch("run_steps.readback")
        _prof.record_host_sync("run_steps.metric_fold")
        labels_np = [np.asarray(a) for a in label_arrays]
        for j in range(k):
            eval_metric.update_dict(
                {n: NDArray(a[j]) for n, a in
                 zip(self._label_names, labels_np)},
                {n: NDArray(o[j]) for n, o in
                 zip(self._output_names, host_outs)})

    def _lower_fused_step(self):
        """Trace+lower one fused training step (no backend compile).
        Requires a bound, optimizer-initialized module with a fresh
        forward() snapshot (i.e. call right after forward())."""
        if not self.optimizer_initialized:
            raise MXNetError("fused step: call init_optimizer() first")
        names = self._update_names()
        if self._fused_step is None:
            self._fused_step = self._build_fused_step(names)
        snapshot = self._exec._snapshot
        if snapshot is None:
            raise MXNetError("fused step: call forward() first")
        arg_vals, aux_vals, key, _ = snapshot
        pvals = tuple(arg_vals[i] for i in self._fused_upd_idx)
        io_vals = tuple(arg_vals[i] for i in self._fused_io_idx)
        states = tuple(tuple(s._data for s in self._opt_states[n])
                       for n in names)
        lrs = tuple(np.float32(1e-3) for _ in names)
        wds = tuple(np.float32(0.0) for _ in names)
        return self._fused_step.lower(
            pvals, io_vals, aux_vals, key, states, lrs, wds,
            jnp.asarray(1, jnp.int32))

    def fused_step_flops(self):
        """XLA cost-analysis FLOPs of one fused training step (for MFU
        reporting)."""
        ca = self._lower_fused_step().cost_analysis()
        if not ca:
            return None
        return float(ca.get("flops", 0.0)) or None

    def fused_step_hlo(self):
        """StableHLO text of the fused training step (pre-backend-opt) —
        the dtype contract is visible here: in bf16 compute_dtype mode
        every convolution/dot must consume bf16 operands (the AMP split
        keeps only statistics/loss in fp32).  Used by tests/test_amp_hlo.py
        to pin the MFU-critical precision layout without a chip."""
        return self._lower_fused_step().as_text()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        """Device-resident when the metric supports it: accumulation
        stays on the async engine (metric.EvalMetric.accumulate_dict)
        and the host only syncs when a callback reads the metric — the
        training loop itself never blocks on a device->host readback
        (was: one asnumpy per output per batch through
        EvalMetric.update)."""
        eval_metric.accumulate_dict(
            dict(zip(self._label_names, labels or [])),
            dict(zip(self._output_names, self.get_outputs())))

    # -- state ---------------------------------------------------------------
    def _sync_params_from_devices(self):
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        """reference: module.py save_optimizer_states."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            import pickle
            import jax
            from .. import profiler as _prof
            # ONE stacked readback for every state tensor (was one
            # np.asarray sync per state), recorded under the host-sync
            # contract like every other deliberate readback site
            states = jax.device_get(
                {n: tuple(s._data for s in st)
                 for n, st in self._opt_states.items()})
            _prof.record_host_sync("module.save_optimizer_states")
            with open(fname, 'wb') as fout:
                pickle.dump(states, fout)

    def load_optimizer_states(self, fname):
        """reference: module.py load_optimizer_states."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            import pickle
            with open(fname, 'rb') as fin:
                # analysis: allow(unsafe-pickle): trusted LOCAL checkpoint file named by the caller — never bytes off the wire (those decode in kvstore_server through the allowlist)
                states = pickle.load(fin)
            for n, st in states.items():
                if n in self._opt_states:
                    for s, v in zip(self._opt_states[n], st):
                        if s is not None:
                            s._set_data(jnp.asarray(v))
            # restored buffers land unsharded; re-apply ZeRO-1 placement
            # immediately or the resume step would hold full O(P)
            # optimizer state per chip — the very peak ZeRO avoids
            self._shard_opt_states()

    def bump_serving_version(self, version=None):
        """Publish the CURRENT server-side weights to serving replicas
        watching this job's parameter servers (the train-and-serve
        topology, docs/SERVING.md).  Requires update-on-kvstore over a
        dist store — in that mode the servers' weights are the live
        weights by construction, so publication is just a version bump
        (:func:`mxnet_tpu.serving.publish_version`); replicas ``pull()``
        the refreshed parameters on their next refresh check."""
        assert self.optimizer_initialized
        if self._kvstore is None or not self._update_on_kvstore \
                or 'dist' not in self._kvstore.type:
            raise MXNetError(
                "bump_serving_version needs update-on-kvstore over a "
                "dist store (the servers must HOLD the live weights a "
                "replica can pull) — init_optimizer(kvstore='dist_async')")
        from ..serving import publish_version
        return publish_version(self._kvstore, version)

    def borrow_optimizer(self, shared_module):
        """Share optimizer/updater/state with another Module
        (reference: module.py borrow_optimizer — BucketingModule makes all
        buckets apply updates through one optimizer)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self._opt_states = shared_module._opt_states
        self.optimizer_initialized = True

    def get_states(self, merge_multi_context=True):
        """Current values of the state inputs, as immutable snapshots
        (reference: module.py get_states — a later set_states must not
        change what the caller saved, e.g. TBPTT save/restore).  The
        returned NDArrays alias the live executor buffers (jnp.asarray is
        zero-copy): the snapshot guarantee rests on jax.Array immutability
        plus set_states REBINDING rather than mutating.  If these buffers
        are ever fed to a donating computation, switch this to a real copy
        (jnp.array(..., copy=True))."""
        assert self.binded and self.params_initialized
        from ..ndarray import NDArray as _ND
        return [_ND(jnp.asarray(self._exec.arg_dict[n]._data))
                for n in self._state_names]

    def set_states(self, states=None, value=None):
        """Set state inputs from arrays or a scalar fill (reference:
        module.py set_states)."""
        assert self.binded and self.params_initialized
        assert (states is None) != (value is None), \
            "provide exactly one of states/value"
        if value is not None:
            for n in self._state_names:
                arr = self._exec.arg_dict[n]
                arr._set_data(jnp.full(arr.shape, value,
                                       np.dtype(arr.dtype)))
            return
        assert len(states) == len(self._state_names), \
            (len(states), self._state_names)
        for n, s in zip(self._state_names, states):
            src = s[0] if isinstance(s, (list, tuple)) else s
            self._exec.arg_dict[n]._set_data(jnp.asarray(src._data))

    def install_monitor(self, mon):
        assert self.binded
        mon.install(self._exec)

    def prepare(self, data_batch):
        pass
