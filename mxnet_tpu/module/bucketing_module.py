"""BucketingModule (reference: python/mxnet/module/bucketing_module.py:35).

One child Module per bucket key, all sharing parameters.  The reference
shares one memory pool between per-bucket executors (shared_module binding,
graph_executor.cc:878); here each bucket is its own jit-compiled XLA
program (one compile per bucket shape — the cache discipline of
SURVEY.md §5.7) and parameter sharing is by reference: every child Module
binds against the SAME arrays, so no copies ever happen on switch.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..initializer import Uniform
from .base_module import BaseModule, _check_input_names
from .module import Module


class BucketingModule(BaseModule):
    """reference: bucketing_module.py:35."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, mesh=None, sharding_rules=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = list(fixed_param_names or [])
        self._state_names = list(state_names or [])
        self._mesh = mesh
        self._sharding_rules = sharding_rules
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        self._monitor = None
        self._grad_req = None

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    def _call_sym_gen(self, *args, **kwargs):
        return self._sym_gen(*args, **kwargs)

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def get_params(self):
        """reference: bucketing_module.py get_params."""
        assert self.binded and self.params_initialized
        return self._curr_module.get_params()

    def get_states(self, merge_multi_context=True):
        """reference: bucketing_module.py get_states — delegates to the
        current bucket's module.  Bucket executors hold independent state
        arrays (shared_module shares parameters only); switch_bucket
        copies the live states across, so the current bucket is always
        authoritative."""
        assert self.binded and self.params_initialized
        return self._curr_module.get_states(merge_multi_context)

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        self._curr_module.set_states(states=states, value=value)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init,
                             allow_extra=allow_extra)
            return
        assert self.binded and self.params_initialized
        # write to the DEFAULT bucket: it is the sync source of truth that
        # _share_params copies from on every non-default forward
        self._buckets[self._default_bucket_key].set_params(
            arg_params, aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self._params_dirty = False

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, 'call bind before initializing the parameters'
        self._buckets[self._default_bucket_key].init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        for mod in self._buckets.values():
            mod.params_initialized = True
        self._params_dirty = False
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req='write'):
        """Bind the default-bucket module
        (reference: bucketing_module.py:313)."""
        assert shared_module is None, \
            'shared_module for BucketingModule is not supported'
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning('Already bound, ignoring bind()')
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._grad_req = grad_req

        symbol, data_names, label_names = self._call_sym_gen(
            self._default_bucket_key)
        module = Module(symbol, data_names, label_names,
                        logger=self.logger, context=self._context,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names,
                        mesh=self._mesh,
                        sharding_rules=self._sharding_rules)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=self._grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Switch to the executor for bucket_key, binding it on first use
        (reference: bucketing_module.py:333)."""
        assert self.binded, 'call bind before switching bucket'
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names,
                            mesh=self._mesh,
                            sharding_rules=self._sharding_rules)
            module.bind(data_shapes, label_shapes, self._curr_module.
                        for_training, self._curr_module.inputs_need_grad,
                        force_rebind=False, shared_module=self._buckets[
                            self._default_bucket_key],
                        grad_req=self._grad_req)
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            if self.params_initialized:
                module.params_initialized = True
            if self.optimizer_initialized:
                module.borrow_optimizer(
                    self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        prev = self._curr_module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key
        # carry RNN states across the switch: bucket executors are
        # separate programs (shared_module shares only params), so the
        # previous bucket's state arrays are copied into the new one —
        # state shapes are batch-sized, not bucket-sized, so they match
        if self._state_names and prev is not None \
                and prev is not self._curr_module \
                and prev.binded and prev.params_initialized \
                and self._curr_module.params_initialized:
            states = prev.get_states()
            cur = self._curr_module.get_states()
            if all(tuple(a.shape) == tuple(b.shape)
                   for a, b in zip(states, cur)):
                self._curr_module.set_states(states=states)
            else:
                # bucket-dependent state shapes: each bucket keeps its
                # own states (the pre-carry behavior); copying would
                # fail deep inside jit with an opaque trace error
                self.logger.debug(
                    'switch_bucket: state shapes differ across buckets; '
                    'not carrying states')

    def _share_params(self, module):
        """Alias the default bucket's param arrays into `module` so all
        buckets update the same storage (replaces the reference's shared
        memory pool)."""
        default = self._buckets[self._default_bucket_key]
        arg, aux = default.get_params()
        module._exec.copy_params_from(arg, aux, allow_extra_params=True)

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning('optimizer already initialized, '
                                'ignoring.')
            return
        self._curr_module.init_optimizer(kvstore, optimizer,
                                         optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    def prepare(self, data_batch):
        """reference: bucketing_module.py prepare."""
        assert self.binded and self.params_initialized
        bucket_key = data_batch.bucket_key
        original_bucket_key = self._curr_bucket_key
        data_shapes = data_batch.provide_data
        label_shapes = data_batch.provide_label
        self.switch_bucket(bucket_key, data_shapes, label_shapes)
        self.switch_bucket(original_bucket_key, None, None)

    def forward(self, data_batch, is_train=None):
        """reference: bucketing_module.py:404."""
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._sync_current()
        self._curr_module.forward(data_batch, is_train=is_train)

    def _sync_current(self):
        """Point the current bucket's executor at the shared params."""
        if self._curr_bucket_key == self._default_bucket_key:
            return
        self._share_params(self._curr_module)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        self._curr_module.update()
        if self._curr_bucket_key != self._default_bucket_key:
            # write updated params back into the default bucket's storage
            arg, aux = self._curr_module.get_params()
            default = self._buckets[self._default_bucket_key]
            default._exec.copy_params_from(arg, aux,
                                           allow_extra_params=True)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        """Delegates to the current bucket's module (device-resident
        accumulation, Module.update_metric): the metric's device state
        lives on the METRIC, not the bucket, so accumulation is
        continuous across bucket switches with no extra syncs."""
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        """reference: bucketing_module.py install_monitor."""
        assert self.binded
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._curr_module.save_checkpoint(prefix, epoch,
                                          save_optimizer_states)
