"""BaseModule: the high-level train/predict interface
(reference: python/mxnet/module/base_module.py).
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from .. import metric as metric_mod
from .. import io as io_mod
from ..model import BatchEndParam
from ..initializer import Uniform
from ..ndarray import NDArray


def _check_input_names(symbol, names, typename, throw):
    """reference: base_module.py _check_input_names."""
    args = symbol.list_arguments()
    for name in names:
        if name in args:
            continue
        candidates = [arg for arg in args if
                      not arg.endswith('_weight') and
                      not arg.endswith('_bias') and
                      not arg.endswith('_gamma') and
                      not arg.endswith('_beta')]
        msg = "\033[91mYou created Module with Module(..., %s_names=%s) but " \
              "input with name '%s' is not found in symbol.list_arguments(). " \
              "Did you mean one of:\n\t%s\033[0m" % (
                  typename, str(names), name, '\n\t'.join(candidates))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


def _parse_data_desc(data_names, label_names, data_shapes, label_shapes):
    """reference: base_module.py _parse_data_desc."""
    data_shapes = [x if isinstance(x, io_mod.DataDesc)
                   else io_mod.DataDesc(*x) for x in data_shapes]
    _check_names_match(data_names, data_shapes, 'data', True)
    if label_shapes is not None:
        label_shapes = [x if isinstance(x, io_mod.DataDesc)
                        else io_mod.DataDesc(*x) for x in label_shapes]
        _check_names_match(label_names, label_shapes, 'label', False)
    else:
        _check_names_match(label_names, [], 'label', False)
    return data_shapes, label_shapes


def _check_names_match(data_names, data_shapes, name, throw):
    actual = [x[0] for x in data_shapes]
    if sorted(data_names) != sorted(actual):
        msg = "Data provided by %s_shapes don't match names specified by " \
              "%s_names (%s vs. %s)" % (name, name, str(data_shapes),
                                        str(data_names))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


def _canon_step_inputs(names, value, what, k=None):
    """Canonicalize ``run_steps`` inputs to a list of K-stacked arrays
    aligned with ``names`` (each element shaped ``(k,) + per_step_shape``).

    Accepts a dict name->array, a list aligned with ``names``, a single
    array (one input), or — for a single input name — a list of K
    per-step batches (stacked here).  Returns (arrays, k)."""
    import jax.numpy as jnp

    def _as_val(v):
        if isinstance(v, NDArray):
            return v._data
        if isinstance(v, (np.ndarray, jnp.ndarray)):
            return v
        # analysis: allow(host-sync): v is user feed data that is NOT an NDArray/jnp array (those returned above) — host lists/scalars only
        return np.asarray(v)

    if value is None:
        if names:
            raise MXNetError(f"run_steps: {what} is required "
                             f"(names: {names})")
        return [], k
    if isinstance(value, dict):
        missing = [n for n in names if n not in value]
        if missing:
            raise MXNetError(f"run_steps: missing {what}: {missing}")
        arrays = [_as_val(value[n]) for n in names]
    elif isinstance(value, (list, tuple)):
        if len(value) == len(names):
            arrays = [_as_val(v) for v in value]
        elif len(names) == 1:
            # list of K per-step batches for the single input
            # analysis: allow(host-sync): K-superbatch staging at run_steps entry — one host stack per K-step dispatch, amortized 1/K per step
            arrays = [np.stack([np.asarray(_as_val(v)) for v in value])]
        else:
            raise MXNetError(
                f"run_steps: expected {len(names)} {what} arrays, "
                f"got {len(value)}")
    else:
        if len(names) != 1:
            raise MXNetError(
                f"run_steps: {what} must be a dict/list covering "
                f"{names}")
        arrays = [_as_val(value)]
    ks = {int(a.shape[0]) for a in arrays if a.ndim}
    if len(ks) != 1:
        raise MXNetError(f"run_steps: inconsistent leading (step) dims "
                         f"for {what}: {sorted(ks)}")
    inferred = ks.pop()
    if inferred == 0:
        raise MXNetError(
            f"run_steps: {what} stacks ZERO steps (empty leading axis) "
            "— a mis-built superbatch (e.g. a KBatchIter tail)?")
    if k is not None and k != inferred:
        raise MXNetError(
            f"run_steps: k={k} but {what} arrays stack "
            f"{inferred} steps (leading dim)")
    return arrays, inferred


class BaseModule:
    """reference: base_module.py BaseModule."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- high-level ----------------------------------------------------------
    def run_steps(self, data, label=None, k=None, eval_metric=None):
        """Run K training steps (forward + backward + optimizer update).

        ``data``/``label`` carry K stacked batches (leading axis = step;
        see :func:`_canon_step_inputs` for accepted forms).  This base
        implementation is the EAGER driver — one dispatch per step — and
        serves as the universal fallback (BucketingModule, K=1, shape
        changes, non-pure optimizers).  :class:`Module` overrides it with
        the scanned single-dispatch program.  Returns the per-step
        outputs stacked on a leading K axis, one NDArray per output."""
        data_arrays, k = _canon_step_inputs(
            self.data_names, data, "data", k)
        label_arrays, k = _canon_step_inputs(
            getattr(self, "label_names", []), label, "label", k)
        return self._run_steps_eager(data_arrays, label_arrays, k,
                                     eval_metric)

    def _run_steps_eager(self, data_arrays, label_arrays, k, eval_metric):
        import jax.numpy as jnp
        outs_steps = []
        for j in range(k):
            batch = io_mod.DataBatch(
                data=[NDArray(jnp.asarray(a[j])) for a in data_arrays],
                label=[NDArray(jnp.asarray(a[j])) for a in label_arrays]
                if label_arrays else None)
            self.forward(batch, is_train=True)
            self.update()
            if eval_metric is not None:
                self.update_metric(eval_metric, batch.label)
            outs_steps.append([o._data for o in self.get_outputs()])
        return [NDArray(jnp.stack([s[i] for s in outs_steps]))
                for i in range(len(outs_steps[0]))]

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """reference: base_module.py score."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            # device-resident accumulation: the loop never blocks on a
            # readback — the metric syncs ONCE at get_name_value below
            # (or whenever a batch_end_callback reads it)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                batch_end_params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                                 eval_metric=eval_metric,
                                                 locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(batch_end_params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """reference: base_module.py iter_predict."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in
                       self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """reference: base_module.py predict."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    'Cannot merge batches, as num of outputs is not the same ' \
                    'in mini-batches. Maybe bucketing is used?'
            # pad slicing already happened on device (above); batches
            # come back in chunked stacked readbacks — one sync per
            # MXNET_PREDICT_READBACK_BATCHES batches instead of one
            # device->host copy per batch per output.  The NDArray
            # wrappers are dropped first so each fetched chunk's device
            # buffers free immediately (the old streaming memory
            # profile, at a fraction of its sync cost).
            groups = [[o._data for o in outs] for outs in output_list]
            del output_list, outputs, out
            host = chunked_device_get(groups, "predict.readback")
            output_list2 = [
                NDArray(np.concatenate([h[i] for h in host]))
                for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric='acc',
            epoch_end_callback=None, batch_end_callback=None, kvstore='local',
            optimizer='sgd', optimizer_params=(('learning_rate', 0.01),),
            eval_end_callback=None,
            eval_batch_end_callback=None, initializer=Uniform(0.01),
            arg_params=None, aux_params=None, allow_missing=False,
            force_rebind=False, force_init=False, begin_epoch=0,
            num_epoch=None, validation_metric=None, monitor=None):
        """Full training loop (reference: base_module.py:376-520)."""
        assert num_epoch is not None, 'please specify number of epochs'

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        ################################################################
        # training loop
        ################################################################
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            end_of_batch = False
            next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                try:
                    next_data_batch = next(data_iter)
                    self.prepare(next_data_batch)
                except StopIteration:
                    end_of_batch = True
                # device-resident metric accumulation: nothing here
                # blocks on the device.  The ONLY host syncs in this
                # loop happen when a batch_end_callback reads the
                # metric (EvalMetric.sync via get_name_value — e.g.
                # Speedometer every `frequent` batches) and at the
                # epoch-end log below: <= nbatch/frequent + 1 syncs
                # per epoch, asserted by tests/test_sync_free.py.
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    batch_end_params = BatchEndParam(
                        epoch=epoch, nbatch=nbatch, eval_metric=eval_metric,
                        locals=locals())
                    for callback in _as_list(batch_end_callback):
                        callback(batch_end_params)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info('Epoch[%d] Train-%s=%f', epoch, name, val)
            toc = time.time()
            self.logger.info('Epoch[%d] Time cost=%.3f', epoch, (toc - tic))

            arg_params_, aux_params_ = self.get_params()
            self.set_params(arg_params_, aux_params_)
            if epoch_end_callback is not None:
                for callback in _as_list(epoch_end_callback):
                    callback(epoch, self.symbol, arg_params_, aux_params_)

            if eval_data:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info('Epoch[%d] Validation-%s=%f', epoch,
                                     name, val)
            train_data.reset()

    # -- abstract interface ---------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        """reference: base_module.py save_params."""
        arg_params, aux_params = self.get_params()
        save_dict = {('arg:%s' % k): v for k, v in arg_params.items()}
        save_dict.update({('aux:%s' % k): v for k, v in aux_params.items()})
        from ..serialization import save_ndarrays
        save_ndarrays(fname, save_dict)

    def load_params(self, fname):
        """reference: base_module.py load_params."""
        from ..serialization import load_ndarrays
        save_dict = load_ndarrays(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(':', 1)
            if arg_type == 'arg':
                arg_params[name] = value
            elif arg_type == 'aux':
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return []

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        assert not states and not value

    def install_monitor(self, mon):
        raise NotImplementedError()

    def prepare(self, data_batch):
        pass

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req='write'):
        raise NotImplementedError()

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        raise NotImplementedError()


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]


def chunked_device_get(groups, tag, chunk=None):
    """Fetch a list of per-batch value groups to host in CHUNKS of
    ``MXNET_PREDICT_READBACK_BATCHES`` batches (default 64): each chunk
    is one stacked ``jax.device_get`` (one host sync, recorded under
    ``tag``), and the chunk's device buffers are released before the
    next chunk is touched.  This keeps predict-style loops at O(1)
    syncs per chunk WITHOUT retaining the whole dataset's outputs in
    device memory the way a single end-of-run device_get would —
    the memory profile the old per-batch asnumpy streaming had, at
    1/chunk of its sync cost.  Mutates ``groups`` in place (device
    values -> numpy) and returns it."""
    import jax
    from ..base import env
    from .. import profiler as _prof
    if chunk is None:
        chunk = max(1, int(env("MXNET_PREDICT_READBACK_BATCHES", 64)))
    for lo in range(0, len(groups), chunk):
        host = jax.device_get(groups[lo:lo + chunk])
        _prof.record_host_sync(tag)
        groups[lo:lo + chunk] = host
    return groups
