"""On-the-wire gradient compression for the dist kvstore push path.

Reference: MXNet 0.12's ``kvstore.set_gradient_compression`` (python/
mxnet/kvstore.py set_gradient_compression; src/kvstore/
gradient_compression.cc) — the 2-bit scheme quantizes every gradient
element to one of {-threshold, 0, +threshold} and keeps the quantization
error as a WORKER-SIDE residual that is added to the next gradient
before quantizing (error feedback), so the error provably drains into
later pushes instead of being lost.  Pull stays full precision: only the
push payload is compressed, matching the reference semantics (the server
stores and serves fp32 weights).

Two wire modes:

* ``2bit`` — 4 elements per byte (16x fewer bytes than fp32) with error
  feedback.  ``threshold`` picks the quantum; elements whose running
  value (gradient + residual) reaches ±threshold fire, the rest wait in
  the residual.
* ``fp16`` — a plain half-precision cast (2x), no residual: the rounding
  error is bounded per push and does not accumulate by construction.

The compressed payload travels as a :class:`WirePayload` whose ``data``
array rides the transport's zero-copy raw-buffer frame
(kvstore_server._send_msg), so enabling compression changes WHAT is
framed, not HOW.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError

_TYPES = ("2bit", "fp16", "none")


class WirePayload:
    """A compressed push payload: (kind, logical shape, threshold, raw
    data array).  Picklable by construction — the transport's skeleton
    walker replaces ``data`` with a raw-buffer placeholder so the bytes
    never pass through pickle."""

    __slots__ = ("kind", "shape", "threshold", "data")

    def __init__(self, kind, shape, threshold, data):
        self.kind = kind
        self.shape = tuple(shape)
        self.threshold = float(threshold)
        self.data = data

    def __reduce__(self):
        return (WirePayload,
                (self.kind, self.shape, self.threshold, self.data))


class RowSparsePayload:
    """A row-sparse value on the wire: (row indices i64, logical row
    count of the destination table, values block).

    ``indices`` is a 1-D sorted strictly-increasing int64 array of the
    touched row ids; ``data`` is either the raw fp row block (one row
    per index, ``data.shape[0] == indices.size``) or a
    :class:`WirePayload` compressing that block.  ``nrows`` pins the
    destination's logical row count so the receiver can range-check the
    ids before touching its table.  Picklable by construction and, like
    WirePayload, framed with ``indices``/``data`` as raw zero-copy
    buffers — only the touched rows (plus 8 bytes per row id) ride the
    wire."""

    __slots__ = ("indices", "nrows", "data")

    def __init__(self, indices, nrows, data):
        self.indices = indices
        self.nrows = int(nrows)
        self.data = data

    def __reduce__(self):
        return (RowSparsePayload, (self.indices, self.nrows, self.data))


def validate_rowsparse(p):
    """Hostile-input gate for a decoded RowSparsePayload: raises
    ValueError unless the indices are a 1-D non-negative strictly
    increasing int64 array that fits in ``nrows`` rows and the values
    block carries exactly one row per index.  Shared by the binary
    codec decoder and the server's pickle-path apply, so a malformed
    descriptor drops the connection instead of corrupting a table."""
    idx = p.indices
    if not isinstance(idx, np.ndarray) or idx.dtype != np.int64 \
            or idx.ndim != 1:
        raise ValueError("row-sparse indices must be a 1-D int64 array")
    nrows = p.nrows
    if not isinstance(nrows, int) or isinstance(nrows, bool) \
            or nrows < 0:
        raise ValueError(
            f"row-sparse nrows must be a non-negative int, got {nrows!r}")
    if idx.size:
        if int(idx[0]) < 0:
            raise ValueError(
                f"row-sparse index out of range: {int(idx[0])}")
        if int(idx[-1]) >= nrows:
            raise ValueError(
                f"row-sparse index {int(idx[-1])} out of range for "
                f"{nrows} rows")
        if idx.size > 1 and not bool(np.all(idx[1:] > idx[:-1])):
            raise ValueError(
                "row-sparse indices must be strictly increasing "
                "(sorted, no duplicates)")
    data = p.data
    if isinstance(data, WirePayload):
        if not data.shape:
            raise ValueError(
                "row-sparse compressed values must keep a row shape")
        got = int(data.shape[0])
    elif isinstance(data, np.ndarray) and data.ndim >= 1:
        got = int(data.shape[0])
    else:
        raise ValueError(
            "row-sparse values must be an ndarray of rows or a "
            "WirePayload")
    if got != idx.size:
        raise ValueError(
            f"row-sparse index/value mismatch: {idx.size} ids vs "
            f"{got} value rows")
    return p


class GradientCompression:
    """Validated compression config + the worker-side compressor."""

    def __init__(self, params):
        params = dict(params or {})
        ctype = params.pop("type", "2bit")
        if ctype not in _TYPES:
            raise MXNetError(
                f"gradient compression type must be one of {_TYPES}, "
                f"got {ctype!r}")
        threshold = float(params.pop("threshold", 0.5))
        if ctype == "2bit" and threshold <= 0:
            raise MXNetError(
                f"gradient compression threshold must be > 0, "
                f"got {threshold}")
        if params:
            raise MXNetError(
                "unknown gradient compression parameter(s): "
                f"{sorted(params)}")
        self.type = ctype
        self.threshold = threshold

    @property
    def active(self) -> bool:
        return self.type != "none"

    def compress(self, wire_key, arr, residuals):
        """Compress one push payload.  ``residuals`` maps wire key ->
        error-feedback residual (fp32, mutated in place for 2bit).
        Non-float payloads pass through uncompressed."""
        if not self.active or arr.dtype not in (np.float32, np.float64):
            return arr
        arr = np.asarray(arr, dtype=np.float32)
        if self.type == "fp16":
            return WirePayload("fp16", arr.shape, 0.0,
                               arr.astype(np.float16))
        payload, residuals[wire_key] = quantize_2bit(
            arr, residuals.get(wire_key), self.threshold)
        return payload

    def compress_rows(self, global_ids, rows, row_residuals):
        """Compress a row-sparse value block.  ``rows`` holds one row
        per entry of ``global_ids``; ``row_residuals`` maps GLOBAL row
        id -> fp32 residual row (mutated in place for 2bit), so a
        restripe can drop exactly the rows that moved servers instead
        of nuking the whole key's residual.  Returns the raw block
        unchanged when inactive or non-float."""
        if not self.active or rows.dtype not in (np.float32, np.float64):
            return rows
        rows = np.asarray(rows, dtype=np.float32)
        if self.type == "fp16":
            return WirePayload("fp16", rows.shape, 0.0,
                               rows.astype(np.float16))
        res = np.zeros(rows.shape, np.float32)
        for j, rid in enumerate(global_ids):
            prev = row_residuals.get(int(rid))
            if prev is not None:
                res[j] = prev
        payload, work = quantize_2bit(rows + res, None, self.threshold)
        for j, rid in enumerate(global_ids):
            row_residuals[int(rid)] = work[j]
        return payload


def quantize_2bit(arr, residual, threshold):
    """Quantize ``arr + residual`` to {-t, 0, +t}, 2 bits per element
    packed 4-per-byte; returns (WirePayload, new_residual)."""
    work = arr.astype(np.float32, copy=True)
    if residual is not None:
        work += residual
    pos = work >= threshold
    neg = work <= -threshold
    # error feedback: what did not fire stays behind for the next push
    work[pos] -= np.float32(threshold)
    work[neg] += np.float32(threshold)
    codes = np.zeros(work.size, np.uint8)
    codes[pos.ravel()] = 1
    codes[neg.ravel()] = 2
    pad = (-codes.size) % 4
    if pad:
        codes = np.concatenate([codes, np.zeros(pad, np.uint8)])
    packed = (codes[0::4] | (codes[1::4] << 2)
              | (codes[2::4] << 4) | (codes[3::4] << 6))
    return (WirePayload("2bit", arr.shape, threshold, packed), work)


def decompress(payload):
    """WirePayload -> the fp32 array the server applies as the
    gradient."""
    if payload.kind == "fp16":
        return np.asarray(payload.data, np.float16).astype(np.float32)
    if payload.kind != "2bit":
        raise MXNetError(
            f"unknown compressed payload kind {payload.kind!r}")
    packed = np.asarray(payload.data, np.uint8)
    n = int(np.prod(payload.shape, dtype=np.int64)) if payload.shape \
        else 1
    codes = np.empty(packed.size * 4, np.uint8)
    codes[0::4] = packed & 3
    codes[1::4] = (packed >> 2) & 3
    codes[2::4] = (packed >> 4) & 3
    codes[3::4] = (packed >> 6) & 3
    codes = codes[:n]
    out = np.zeros(n, np.float32)
    out[codes == 1] = np.float32(payload.threshold)
    out[codes == 2] = np.float32(-payload.threshold)
    return out.reshape(payload.shape)
