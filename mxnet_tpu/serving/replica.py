"""Serving replica: a model server speaking the hardened kvstore wire.

One process (or thread) = one replica: it loads a checkpoint into a
:class:`~mxnet_tpu.serving.bucketed.BucketedPredictor`, accepts the
same zero-copy frames / allowlisted decode / exactly-once envelopes as
a parameter server (it IS a :class:`~mxnet_tpu.kvstore_server.
KVStoreServer` subclass — the serving envelope types are extension ops
on the existing dispatch), and answers:

* ``("predict", {name: array})`` — through the dynamic batcher; reply
  payload ``("result", version, [outputs])`` or the typed
  ``("busy", {queue_depth, limit})`` shed signal.
* ``("serving_stats",)`` — version, queue depth, batch/shed counters
  and the profiler's p50/p99/QPS latency dict.
* ``("serving_refresh",)`` — force one weight-version check against the
  live parameter servers NOW (the deterministic form of the background
  poll).

**Pipelined connections.**  The base server handles one request per
connection at a time — correct for a parameter shard, fatal for a
batcher (a pipelined client's second request would wait on the first's
reply, so batches could never form across one connection).  The replica
overrides ``_serve_conn`` with a read-ahead loop: envelopes are decoded
as they arrive, predict ops park a reply slot in the batcher, and a
writer thread sends completed replies in STRICT arrival order — the
FIFO ack contract the client window replay machinery assumes is
preserved exactly.  Predict is pure, so a replayed predict after a
reconnect is simply re-run: it needs no dedup window entry.

**Train-and-serve.**  With ``param_servers=`` (or ``MXT_SERVER_URIS``)
the replica holds a worker-side kvstore client to the SAME dist_async
cluster a trainer updates.  A version bump
(:func:`mxnet_tpu.serving.publish_version`) makes the next refresh
check ``pull()`` every served parameter and swap it in hot — one
process tree trains and serves, the ROADMAP's millions-of-users
scenario.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..base import MXNetError, env
from .. import wirecodec as _codec
from ..kvstore_server import KVStoreServer, _send_msg, _recv_msg
from .. import profiler as _prof
from .. import tracing as _tr
from .. import health as _health
from .. import faultinject as _fi
from .batcher import DynamicBatcher, _ReplySlot
from .bucketed import BucketedPredictor

#: kvstore key carrying the published weight version (a 1-element
#: float64 register written with the updater-bypassing "assign" op)
VERSION_KEY = "__mxt_serving_version__"


class ServingReplica(KVStoreServer):
    """One inference replica on the kvstore wire."""

    def __init__(self, symbol, data_shapes: Dict[str, tuple], arg_params,
                 aux_params=None, buckets=None, compute_dtype=None,
                 host="127.0.0.1", port=0, param_servers=None,
                 refresh_interval=None, max_wait_s=None, queue_depth=None,
                 warmup=True):
        super().__init__(server_id=0, num_workers=1, host=host, port=port)
        self._predictor = BucketedPredictor(
            symbol, data_shapes, arg_params, aux_params=aux_params,
            buckets=buckets, compute_dtype=compute_dtype)
        if warmup:
            self._predictor.warmup()
        self._batcher = DynamicBatcher(self._predictor,
                                       max_wait_s=max_wait_s,
                                       queue_depth=queue_depth)
        # predict bypasses the exactly-once dedup window on purpose: it
        # is PURE, so a post-reconnect replay re-runs harmlessly — and
        # must not hold a conn thread inside _exactly_once while the
        # batch forms (that would serialize the batcher per connection)
        self._deferred_ops = {"predict", "predict_canary"}
        # protocol: replay(pure) reply(predictions) codec(binary)
        self.register_op("predict", self._op_predict_sync)
        # the canary-tagged twin of predict: same batcher, same reply
        # shape, but counted separately (serving.canary_predict) so a
        # fleet's canary fraction is provable server-side; rides pickle
        # (the canary cohort is a fraction — never the hot path)
        # protocol: replay(pure) reply(predictions)
        self.register_op("predict_canary", self._op_predict_sync)
        # protocol: replay(pure) reply(serving stats dict)
        self.register_op("serving_stats", self._op_stats)
        # protocol: replay(idempotent) reply(version + refreshed)
        self.register_op("serving_refresh", self._op_refresh)
        # operator drain: an advisory flag the stats reply carries —
        # routers stop sending new work, in-flight requests finish
        # normally (("drain", False) undoes it; setting the same flag
        # twice is a no-op, hence idempotent)
        # protocol: replay(idempotent) reply(draining flag)
        self.register_op("drain", self._op_drain)
        self._draining = False
        if param_servers is None:
            import os
            param_servers = os.environ.get("MXT_SERVER_URIS") or None
        self._ps_uris = param_servers
        self._ps = None
        self._ps_lock = threading.Lock()
        self._seen_version: Optional[int] = None
        self.refreshes = 0
        self._refresh_interval = float(
            env("MXNET_SERVING_REFRESH_S", 0.0)
            if refresh_interval is None else refresh_interval)
        self._refresh_thread = None
        if self._refresh_interval > 0 and self._ps_uris:
            self._refresh_thread = threading.Thread(
                target=self._refresh_loop, daemon=True)
            self._refresh_thread.start()
        # the health watchdog samples the batcher queue every tick:
        # depth at (or past) MXNET_HEALTH_QUEUE_SAT x limit trips a
        # typed queue_saturated event and degrades this replica's
        # status — the serving half of the SLO plane.  Keyed by port:
        # two in-process replicas (tests, train-and-serve topologies)
        # must not overwrite each other's probe — and one replica's
        # stop() must not unregister the survivor's
        self._health_probe_name = "serving.queue:%d" % self.port
        _health.register_probe(self._health_probe_name,
                               self._health_probe)

    def _health_probe(self) -> dict:
        return {"queue_depth": self._batcher.queue_depth,
                "queue_limit": self._batcher.queue_limit}

    @classmethod
    def from_checkpoint(cls, prefix, epoch, data_shapes, **kwargs):
        """Load ``prefix-%04d.params`` (classic or sharded format — see
        :func:`mxnet_tpu.checkpoint.load_serving_params`) and serve it."""
        from ..checkpoint import load_serving_params
        sym, args, auxs = load_serving_params(prefix, epoch)
        if sym is None:
            raise MXNetError(f"no symbol file at {prefix}-symbol.json — "
                             "a replica needs the graph, not just weights")
        return cls(sym, data_shapes, args, aux_params=auxs, **kwargs)

    # -- properties ----------------------------------------------------------
    @property
    def version(self) -> int:
        return self._predictor.version

    @property
    def buckets(self):
        return list(self._predictor.buckets)

    # -- serving envelope handlers -------------------------------------------
    def _dispatch_deferred(self, inner, span=None) -> _ReplySlot:
        """Pipelined path: park the predict in the batcher, return the
        reply slot the connection writer awaits (``span`` attaches to
        the slot BEFORE it is queued — see DynamicBatcher.submit)."""
        if inner and inner[0] == "predict_canary":
            _prof.record_channel_event("serving.canary_predict")
        payload = inner[1] if len(inner) > 1 else None
        return self._batcher.submit(payload, span=span)

    def _op_predict_sync(self, msg, rank):
        """Raw-message / legacy fallback: same batcher, awaited inline."""
        slot = self._batcher.submit(msg[1] if len(msg) > 1 else None)
        slot.done.wait()
        status, payload = slot.reply
        if status != "ok":
            raise MXNetError(str(payload))
        return payload

    def _op_stats(self, msg, rank):
        return {
            "version": self._predictor.version,
            "buckets": list(self._predictor.buckets),
            "queue_depth": self._batcher.queue_depth,
            "queue_limit": self._batcher.queue_limit,
            "batches": self._batcher.batches,
            "shed": self._batcher.shed,
            "refreshes": self.refreshes,
            # the operator drain flag (("drain",) envelope): advisory —
            # a fleet router treats a draining replica as ineligible
            # for NEW work while everything in flight completes
            "draining": self._draining,
            # which membership epoch the weight-refresh client last
            # converged onto (0 = static roster or no client yet): lets
            # an operator correlate a served-version stall with training
            # -cluster churn from the serving side alone
            "roster_generation": getattr(self._ps, "_roster_gen", 0) or 0,
            # which bootstrap slot leads the training roster (-1 = a
            # joined-later server) and how many coordinator successions
            # the refresh client has ridden: a FAILOVER is observable
            # from the serving side without log-diving
            "coordinator_slot": getattr(self._ps, "_coordinator_slot",
                                        0) or 0,
            "coordinator_failovers": getattr(self._ps, "_failovers",
                                             0) or 0,
            "latency": _prof.latency_stats("serving.request"),
            # the replica's health verdict next to its SLO numbers: a
            # BUSY storm or saturated queue reads as DEGRADED here (and
            # recovers with hysteresis — no flapping), so a router can
            # steer on serving_stats alone (docs/OBSERVABILITY.md)
            "health": _health.snapshot_section(compact=True),
        }

    def _op_refresh(self, msg, rank):
        return self._refresh_once()

    def _op_drain(self, msg, rank):
        """Operator drain toggle: ``("drain",)`` / ``("drain", True)``
        marks this replica draining, ``("drain", False)`` restores it.
        Advisory by design — the stats reply carries the flag and a
        router stops ROUTING here, while requests already in flight
        (and any client that ignores the flag) still complete: a drain
        must never fail the work it is trying to move elsewhere."""
        enable = bool(msg[1]) if len(msg) > 1 else True
        changed = enable != self._draining
        self._draining = enable
        if changed:
            _prof.record_channel_event("serving.drain" if enable
                                       else "serving.undrain")
            _health.note("serving_drain", enabled=enable, port=self.port)
        return {"draining": enable}

    def _stats_payload(self):
        """The universal ``("stats",)`` envelope, serving-flavored: the
        base server's full profiler snapshot plus the old
        ``serving_stats`` dict under ``serving`` — one stats op for the
        whole cluster, and ``serving_stats`` stays answering for
        existing clients (it IS the ``serving`` section)."""
        snap = super()._stats_payload()
        snap["serving"] = self._op_stats(None, None)
        return snap

    # -- weight refresh (live dist_async parameter servers) ------------------
    def _ps_client(self):
        if self._ps_uris is None:
            raise MXNetError(
                "this replica has no parameter servers to refresh from "
                "(pass param_servers= or set MXT_SERVER_URIS)")
        with self._ps_lock:
            if self._ps is None:
                from ..kvstore import KVStoreDistAsync
                # roster_member=False: under MXNET_KVSTORE_ELASTIC this
                # client FOLLOWS the training roster (a server evicted
                # between version pulls repairs transparently mid-pull)
                # but must never JOIN it — a replica registering as a
                # worker rank would inflate every training barrier, and
                # its close() would evict the real rank sharing its id
                self._ps = KVStoreDistAsync(uris=self._ps_uris,
                                            roster_member=False)
            return self._ps

    @staticmethod
    def _is_missing_key(exc) -> bool:
        """A pull that failed because the key was never init'ed on the
        servers (frozen param / version not yet published) — the ONE
        failure a refresh may shrug off.  Transport faults must NOT be
        filed here: skipping a param on a connection blip while still
        advancing the seen version would serve stale weights until the
        NEXT bump."""
        return "uninitialized key" in str(exc)

    def _drop_ps(self):
        """Discard the (possibly hard-poisoned) parameter-server client
        so the next refresh attempt re-dials fresh connections instead
        of re-raising the same channel poison forever."""
        with self._ps_lock:
            ps, self._ps = self._ps, None
        if ps is not None:
            try:
                ps.close()
            except Exception:  # noqa: BLE001 — already-dead channels
                pass

    def _published_version(self) -> Optional[int]:
        from ..ndarray import zeros as nd_zeros
        out = nd_zeros((1,), dtype="float64")
        try:
            self._ps_client().pull(VERSION_KEY, out=out)
        except MXNetError as exc:
            if self._is_missing_key(exc):
                return None   # no version published yet
            # transport failure: surface it (the poll loop counts it,
            # a forced serving_refresh errs to the client) and re-dial
            # next time — a dead channel must not masquerade as
            # "nothing published"
            self._drop_ps()
            raise
        return int(round(float(out.asnumpy()[0])))

    def _refresh_once(self) -> dict:
        """Check the published version; on a bump, ``pull()`` every
        served parameter from the live servers and hot-swap.  Returns
        {version, refreshed, skipped}.  Raises on transport failure
        WITHOUT advancing the seen version, so the next poll retries
        the same bump."""
        published = self._published_version()
        if published is None or published == self._seen_version:
            return {"version": self._predictor.version,
                    "refreshed": False, "skipped": []}
        ps = self._ps_client()
        from ..ndarray import zeros as nd_zeros
        fresh, skipped = {}, []
        for name, (shape, dtype) in self._predictor.param_specs().items():
            out = nd_zeros(shape, dtype=np.dtype(dtype))
            try:
                ps.pull(name, out=out)
            except MXNetError as exc:
                if self._is_missing_key(exc):
                    # a param the trainer never pushed (fixed/frozen):
                    # keep the checkpoint value
                    skipped.append(name)
                    continue
                self._drop_ps()
                raise
            fresh[name] = out
        if fresh:
            current = self._predictor.current_params()
            current.update(fresh)
            self._predictor.set_params(current, version=published)
        self._seen_version = published
        self.refreshes += 1
        _prof.record_channel_event("serving.weight_refresh")
        return {"version": self._predictor.version, "refreshed": True,
                "skipped": skipped}

    def _refresh_loop(self):
        while not self._stop.wait(self._refresh_interval):
            try:
                self._refresh_once()
            except Exception:  # noqa: BLE001 — poll must outlive blips
                # a refresh failure (servers restarting, transient net)
                # must not kill the poll: the replica keeps serving the
                # CURRENT weights and the next tick retries; the counter
                # makes the misses observable
                _prof.record_channel_event("serving.refresh_error")

    # -- pipelined connection loop -------------------------------------------
    def _serve_conn(self, conn):
        """Read-ahead request loop with in-order replies (see module
        docstring).  Decode errors (hostile frames) tear the connection
        down exactly like the base server: the exception leaves the
        loop, the connection closes, other clients are untouched."""
        import queue as _queue
        slots: _queue.Queue = _queue.Queue()
        writer = threading.Thread(target=self._reply_writer,
                                  args=(conn, slots), daemon=True)
        writer.start()
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        msg = _recv_msg(conn)
                    except (ConnectionError, OSError):
                        return
                    slots.put(self._admit(msg, conn))
        except Exception:  # noqa: BLE001 — hostile frame / conn death
            pass
        finally:
            slots.put(None)
            writer.join(timeout=30.0)

    def _admit(self, msg, conn):
        """Turn one decoded message into a reply slot: deferred serving
        ops park in the batcher, codec hellos register the connection,
        everything else completes inline through the base server's
        exactly-once machinery."""
        if msg and msg[0] == "req":
            _, cid, seq, inner = msg[:4]
            wctx = msg[4] if len(msg) > 4 else None
            if inner and inner[0] in self._deferred_ops:
                if isinstance(cid, (tuple, list)) and cid:
                    self._note_ping(cid[0])
                # DETACHED span, begun BEFORE the batcher sees the slot
                # (attaching after submit would race the batcher's
                # queue-wait annotation) and ended by the reply writer
                # once the slot completes — it covers the request's
                # whole replica stay (queue wait + padded forward),
                # child of the client-side call when the envelope
                # carried a trace field
                sp = None
                if _tr.enabled():
                    sp = _tr.span_begin(
                        "srv.predict", cat="server", detach=True,
                        ctx=(wctx[0], wctx[1]) if wctx else None,
                        args=({"client_send_us": float(wctx[2])}
                              if wctx and len(wctx) > 2 else None))
                slot = self._dispatch_deferred(inner, span=sp)
                slot.role = "server"
                return slot
            cidt = tuple(cid) if isinstance(cid, list) else cid
            reply = self._traced_exactly_once(cidt, seq, inner, wctx)
            return _CompletedSlot(reply, "server")
        hello = _codec.handle_hello(conn, msg)
        if hello is not None:
            return _CompletedSlot(hello, None, byte_kind="control")
        try:
            reply = ("ok", self._handle(msg))
        except Exception as exc:  # noqa: BLE001 — to the client
            reply = ("err", f"{type(exc).__name__}: {exc}")
        if msg and msg[0] == "ping":
            return _CompletedSlot(reply, None, byte_kind="control")
        return _CompletedSlot(reply, None)

    def _reply_writer(self, conn, slots):
        """Send completed replies in arrival order (the client's window
        machinery pops acks FIFO — order is part of the wire contract)."""
        try:
            while True:
                slot = slots.get()
                if slot is None:
                    return
                slot.done.wait()
                _tr.span_end(getattr(slot, "span", None))
                try:
                    _send_msg(conn, slot.reply,
                              fi_role=getattr(slot, "role", None),
                              byte_kind=getattr(slot, "byte_kind",
                                                "sent"))
                except (ConnectionError, OSError):
                    # client gone mid-reply: predict is pure, so the
                    # reconnect replay simply re-runs it — drain the
                    # remaining slots without sending
                    return
                if getattr(slot, "role", None) == "server":
                    # the serving tier honors the same deterministic
                    # kill dial as the base serve loop: SIGKILL after
                    # exactly N enveloped replies (the chaos gate's
                    # mid-storm replica death)
                    _fi.server_replied()
        except Exception:  # noqa: BLE001 — conn died; client reconnects
            pass

    def stop(self):
        super().stop()
        _health.unregister_probe(self._health_probe_name)
        self._batcher.stop()
        if self._ps is not None:
            try:
                self._ps.close()
            except MXNetError:
                pass


class _CompletedSlot:
    """Adapter giving an already-computed reply the _ReplySlot shape the
    writer consumes."""

    __slots__ = ("done", "reply", "role", "byte_kind")
    _DONE = threading.Event()
    _DONE.set()

    def __init__(self, reply, role, byte_kind="sent"):
        self.done = self._DONE
        self.reply = reply
        self.role = role
        self.byte_kind = byte_kind
