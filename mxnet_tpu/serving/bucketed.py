"""Bucketed pre-compiled predict executables.

# analysis: hot-path

The serving analog of the trainer's fused-step discipline: a replica
must never compile in the request path more than once per BUCKET.  The
predictor pre-compiles one XLA forward program per configured batch
size (``MXNET_SERVING_BUCKETS``); a batch of n requests pads to the
smallest covering bucket and slices the padded rows off before the
reply, so serving N distinct request sizes costs ``len(buckets)``
compiles, not N (TF-Serving's bucketed-batching shape,
arXiv:1605.08695 §4; the reference analog is BucketingModule's
per-bucket executor sharing one parameter set, module/
bucketing_module.py).

Weight refresh is a data swap, not a recompile: parameters enter the
jitted forward as ARGUMENTS, so :meth:`BucketedPredictor.set_params`
replaces the value tuple under a lock and every later predict serves
the new version — the live train-and-serve path rides this.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from ..base import MXNetError, env
from ..executor import build_interpreter
from .. import profiler as _prof


def parse_buckets(spec=None) -> List[int]:
    """Canonical bucket list from a spec string/iterable (default: the
    ``MXNET_SERVING_BUCKETS`` knob): sorted, deduped, all positive."""
    if spec is None:
        spec = env("MXNET_SERVING_BUCKETS", "1,2,4,8,16,32")
    if isinstance(spec, str):
        items = [s for s in spec.replace(" ", "").split(",") if s]
    else:
        items = list(spec)
    try:
        buckets = sorted({int(b) for b in items})
    except (TypeError, ValueError):
        raise MXNetError(f"bad serving bucket spec {spec!r}: expected "
                         "comma-separated positive batch sizes")
    if not buckets or buckets[0] < 1:
        raise MXNetError(f"bad serving bucket spec {spec!r}: buckets "
                         "must be >= 1")
    return buckets


class BucketedPredictor:
    """Checkpoint -> bucketed predict executables with hot weight swap.

    ``data_shapes`` maps each data input name to its per-example
    FEATURE shape (no batch dim); every other symbol input (labels a
    loss head declares) is fed cached zeros — eval-mode loss heads
    (SoftmaxOutput & co.) ignore labels, exactly like
    ``Module.predict``.
    """

    def __init__(self, symbol, data_shapes: Dict[str, tuple], arg_params,
                 aux_params=None, buckets=None, compute_dtype=None,
                 data_dtypes: Optional[Dict[str, object]] = None):
        import jax
        self._sym = symbol
        self._run, self._arg_names, self._aux_names = build_interpreter(
            symbol, compute_dtype)
        self._data_shapes = {n: tuple(int(d) for d in s)
                             for n, s in dict(data_shapes).items()}
        unknown = [n for n in self._data_shapes
                   if n not in self._arg_names]
        if unknown:
            raise MXNetError(f"data_shapes name(s) {unknown} are not "
                             f"inputs of the symbol ({self._arg_names})")
        self._data_names = [n for n in self._arg_names
                            if n in self._data_shapes]
        self._data_dtypes = {
            n: np.dtype((data_dtypes or {}).get(n, np.float32))
            for n in self._data_names}
        self._param_names = [n for n in self._arg_names
                             if n not in self._data_shapes
                             and n in dict(arg_params)]
        self._extra_inputs = [n for n in self._arg_names
                              if n not in self._data_shapes
                              and n not in self._param_names]
        self.buckets = parse_buckets(buckets)
        self._lock = threading.Lock()
        self._params: Dict[str, object] = {}
        self._aux: Dict[str, object] = {}
        self.version = 0
        self._bucket_inputs: Dict[int, Dict[str, object]] = {}
        self._compiled = set()   # buckets whose executable was built
        self._key = jax.random.PRNGKey(0)   # eval mode: RNG ops inert

        def _fwd(arg_vals, aux_vals, key):
            outs, _new_aux = self._run(arg_vals, aux_vals, key, False)
            return outs

        self._jit = jax.jit(_fwd)
        self.set_params(arg_params, aux_params, version=0)

    # -- weights -------------------------------------------------------------
    def set_params(self, arg_params, aux_params=None, version=None):
        """Swap the served weights IN PLACE (no recompile: params are
        jit arguments).  Values are cast to the incumbent dtype/shape —
        a refresh can change numbers, never the compiled signature."""
        import jax.numpy as jnp
        arg_params = dict(arg_params)
        missing = [n for n in self._param_names if n not in arg_params]
        if missing:
            raise MXNetError(f"set_params: missing parameter(s) {missing}")
        new_p, new_a = {}, {}
        for name in self._param_names:
            v = jnp.asarray(_raw(arg_params[name]))
            old = self._params.get(name)
            if old is not None:
                if tuple(v.shape) != tuple(old.shape):
                    raise MXNetError(
                        f"set_params: shape of {name!r} changed "
                        f"{tuple(old.shape)} -> {tuple(v.shape)} — a "
                        "weight refresh cannot re-architect the model")
                if v.dtype != old.dtype:
                    v = v.astype(old.dtype)
            new_p[name] = v
        for name in self._aux_names:
            src = (aux_params or {}).get(name)
            if src is None:
                src = self._aux.get(name)
            if src is None:
                raise MXNetError(f"set_params: missing aux state {name!r}")
            v = jnp.asarray(_raw(src))
            old = self._aux.get(name)
            if old is not None and v.dtype != old.dtype:
                v = v.astype(old.dtype)
            new_a[name] = v
        with self._lock:
            self._params = new_p
            self._aux = new_a
            self.version = int(self.version + 1 if version is None
                               else version)

    def param_specs(self) -> Dict[str, tuple]:
        """{name: (shape, dtype_str)} of the served parameters — what a
        weight-refresh pull needs to allocate its out arrays."""
        with self._lock:
            return {n: (tuple(v.shape), str(v.dtype))
                    for n, v in self._params.items()}

    def current_params(self) -> Dict[str, object]:
        """Snapshot of the served parameter values (for a partial
        refresh to merge fresh pulls over)."""
        with self._lock:
            return dict(self._params)

    # -- buckets -------------------------------------------------------------
    def select_bucket(self, n: int) -> int:
        """Smallest bucket covering ``n`` rows (the largest bucket for
        oversized batches — the caller chunks).  Pure and deterministic:
        tests pin it directly."""
        if n < 1:
            raise MXNetError(f"select_bucket: need >= 1 row, got {n}")
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _bucket_extra_inputs(self, bucket: int) -> Dict[str, object]:
        """Cached zero arrays for the non-data, non-param inputs at this
        bucket's batch size (label inputs of loss heads; ignored in eval
        mode)."""
        cached = self._bucket_inputs.get(bucket)
        if cached is not None:
            return cached
        import jax.numpy as jnp
        shapes = {n: (bucket,) + s for n, s in self._data_shapes.items()}
        arg_shapes, _out, _aux = self._sym.infer_shape(**shapes)
        by_name = dict(zip(self._arg_names, arg_shapes))
        extras = {n: jnp.zeros(tuple(by_name[n]), jnp.float32)
                  for n in self._extra_inputs}
        self._bucket_inputs[bucket] = extras
        return extras

    # -- predict -------------------------------------------------------------
    def predict(self, data: Dict[str, np.ndarray]):
        """Run one padded-bucket forward per <= max(buckets)-row chunk;
        returns ``(version, [np outputs sliced to the true row count])``.

        ``data`` maps every data input name to an (n, *feature) array;
        rows beyond n are zero padding and are sliced off HERE — padding
        is an executable-shape artifact that must never leak into a
        reply."""
        datas = {}
        n = None
        for name in self._data_names:
            if name not in data:
                raise MXNetError(f"predict: missing data input {name!r}")
            # analysis: allow(host-sync): request payloads arrive as HOST numpy views off the wire frame — nothing here reads a device buffer back
            arr = np.asarray(_raw(data[name]))
            want = self._data_shapes[name]
            if tuple(arr.shape[1:]) != want:
                raise MXNetError(
                    f"predict: {name!r} feature shape {tuple(arr.shape[1:])}"
                    f" != served shape {want}")
            if n is None:
                n = int(arr.shape[0])
            elif int(arr.shape[0]) != n:
                raise MXNetError("predict: data inputs disagree on the "
                                 "row count")
            # dtype is part of the compiled signature: cast instead of
            # letting a float64 client request force a recompile
            datas[name] = np.ascontiguousarray(
                arr, dtype=self._data_dtypes[name])
        if n is None or n < 1:
            raise MXNetError("predict: empty request")
        chunks = []
        version = None
        max_b = self.buckets[-1]
        for lo in range(0, n, max_b):
            hi = min(n, lo + max_b)
            v, outs = self._predict_chunk(
                {name: arr[lo:hi] for name, arr in datas.items()}, hi - lo)
            version = v if version is None else version
            chunks.append(outs)
        if len(chunks) == 1:
            return version, chunks[0]
        return version, [np.concatenate(parts, axis=0)
                         for parts in zip(*chunks)]

    def _predict_chunk(self, datas, n):
        import jax
        bucket = self.select_bucket(n)
        pad = bucket - n
        with self._lock:
            params = self._params
            aux = self._aux
            version = self.version
        extras = self._bucket_extra_inputs(bucket)
        arg_vals = []
        for name in self._arg_names:
            if name in datas:
                arr = datas[name]
                if pad:
                    arr = np.concatenate(
                        [arr, np.zeros((pad,) + arr.shape[1:], arr.dtype)],
                        axis=0)
                arg_vals.append(arr)
            elif name in params:
                arg_vals.append(params[name])
            else:
                arg_vals.append(extras[name])
        aux_vals = tuple(aux[name] for name in self._aux_names)
        if bucket not in self._compiled:
            # one executable build per bucket, ever — THE serving compile
            # pin (tests assert dispatch_counts()["serving.predict_compile"]
            # <= len(buckets) after any request mix)
            self._compiled.add(bucket)
            _prof.record_dispatch("serving.predict_compile")
        _prof.record_dispatch("serving.predict")
        with _prof.scope("serving_predict", "symbolic"):
            outs = self._jit(tuple(arg_vals), aux_vals, self._key)
        host = jax.device_get(outs)
        # the reply crosses the wire as host bytes: this readback is the
        # serving loop's one deliberate sync, counted like every other
        # contract site (docs/PERF_NOTES.md round 8)
        _prof.record_host_sync("serving.predict_readback")
        return version, [np.asarray(o)[:n] for o in host]

    def warmup(self):
        """Pre-compile every bucket with a zero batch, so the first real
        request never pays a compile (the 'pre-compiled' half of the
        tentpole).  Returns the number of buckets built."""
        for b in self.buckets:
            self._predict_chunk(
                {name: np.zeros((b,) + s, self._data_dtypes[name])
                 for name, s in self._data_shapes.items()}, b)
        return len(self.buckets)


def _raw(v):
    """Underlying array of an NDArray / jax.Array / numpy value."""
    data = getattr(v, "_data", None)
    return data if data is not None else v
