"""Serving client: pipelined predict requests over the kvstore channel.

Reuses :class:`mxnet_tpu.kvstore._ServerConn` verbatim — the serving
wire IS the hardened kvstore wire, so a client gets the sliding-window
pipeline (``MXNET_SERVING_CLIENT_WINDOW`` envelopes in flight — wide by
default so the replica's batcher sees real concurrency from one
connection), reconnect + full-window replay through connection kills,
heartbeat liveness and TCP_NODELAY for free.  Replies are typed:

* a served result returns ``(version, [np outputs])``;
* an admission-control shed raises :class:`BusyError` (retryable — the
  model never ran);
* a real failure raises :class:`~mxnet_tpu.base.MXNetError`.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..base import MXNetError, env
from .batcher import BusyError
from .bucketed import _raw


class PredictFuture:
    """Handle for one in-flight predict; ``get()`` blocks for the typed
    reply."""

    __slots__ = ("_pending", "version")

    def __init__(self, pending):
        self._pending = pending
        self.version = None

    def get(self):
        from ..kvstore import _await
        payload = _await(self._pending)   # raises MXNetError on "err"
        if payload[0] == "busy":
            info = payload[1]
            raise BusyError(
                "serving replica shed the request (queue depth "
                f"{info.get('queue_depth')} >= limit {info.get('limit')})"
                " — retry with backoff or use another replica")
        _tag, version, outs = payload
        self.version = int(version)
        return [np.asarray(o) for o in outs]


class ServingClient:
    """Client for one :class:`~mxnet_tpu.serving.ServingReplica`."""

    def __init__(self, uri, window=None, connect_timeout=60.0):
        from ..kvstore import _ServerConn
        w = int(env("MXNET_SERVING_CLIENT_WINDOW", 64)
                if window is None else window)
        self._conn = _ServerConn(uri, connect_timeout=connect_timeout,
                                 window=max(1, w))

    def predict_async(self, data, name="data") -> PredictFuture:
        """Enqueue one predict; returns a :class:`PredictFuture`.  Many
        futures may be outstanding — that is exactly what feeds the
        replica's dynamic batcher."""
        payload = self._payload(data, name)
        return PredictFuture(self._conn.request(("predict", payload)))

    def predict(self, data, name="data"):
        """Blocking predict: returns the output list (np arrays, padded
        rows already sliced off by the replica)."""
        return self.predict_async(data, name=name).get()

    @staticmethod
    def _payload(data, name) -> Dict[str, np.ndarray]:
        if not isinstance(data, dict):
            data = {name: data}
        out = {}
        for k, v in data.items():
            arr = np.asarray(_raw(v))
            # ndim check BEFORE ascontiguousarray: the latter promotes
            # 0-d to 1-d and would mask a scalar input
            if arr.ndim < 1:
                raise MXNetError(f"predict input {k!r} needs a batch axis")
            out[str(k)] = np.ascontiguousarray(arr)
        return out

    def stats(self) -> dict:
        """The replica's serving counters (version, queue depth,
        batches, shed count, p50/p99/QPS latency dict)."""
        return self._conn.submit(("serving_stats",), wait=True)

    def refresh(self) -> dict:
        """Force one weight-version check on the replica NOW; returns
        {version, refreshed, skipped}."""
        return self._conn.submit(("serving_refresh",), wait=True)

    def version(self) -> Optional[int]:
        return self.stats().get("version")

    def close(self):
        self._conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
