"""Serving client: pipelined predict requests over the kvstore channel.

Reuses :class:`mxnet_tpu.kvstore._ServerConn` verbatim — the serving
wire IS the hardened kvstore wire, so a client gets the sliding-window
pipeline (``MXNET_SERVING_CLIENT_WINDOW`` envelopes in flight — wide by
default so the replica's batcher sees real concurrency from one
connection), reconnect + full-window replay through connection kills,
heartbeat liveness and TCP_NODELAY for free.  Replies are typed:

* a served result returns ``(version, [np outputs])``;
* an admission-control shed raises :class:`BusyError` (retryable — the
  model never ran);
* a reply that never arrives within an explicit ``get(timeout=...)``
  raises :class:`PredictTimeout` (retryable on ANOTHER replica —
  predict is pure, and a gray-failed replica that accepted the request
  but will never answer is indistinguishable from a slow one except by
  this clock);
* a real failure raises :class:`~mxnet_tpu.base.MXNetError`.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..base import MXNetError, env
from .batcher import BusyError
from .bucketed import _raw


class PredictTimeout(MXNetError):
    """A predict (or control op) reply did not arrive within the
    caller's timeout.  The connection may be fine and merely slow, or
    gray-failed (accepting requests, never replying) — either way the
    request is safe to retry elsewhere, because predict is pure."""


def _timed_await(pending, timeout, what="request"):
    """Block for a ``_Pending`` reply with an optional timeout —
    the fleet's per-attempt clock on every wire op (kvstore._await is
    the unbounded form)."""
    if not pending.done.wait(timeout):
        raise PredictTimeout(
            f"serving {what} reply not received within {timeout}s")
    if pending.error is not None:
        raise MXNetError(f"kvstore server request failed: "
                         f"{pending.error}")
    return pending.value


class PredictFuture:
    """Handle for one in-flight predict; ``get()`` blocks for the typed
    reply."""

    __slots__ = ("_pending", "version")

    def __init__(self, pending):
        self._pending = pending
        self.version = None

    def get(self, timeout: Optional[float] = None):
        payload = _timed_await(self._pending, timeout, what="predict")
        if payload[0] == "busy":
            info = payload[1]
            raise BusyError(
                "serving replica shed the request (queue depth "
                f"{info.get('queue_depth')} >= limit {info.get('limit')})"
                " — retry with backoff or use another replica")
        _tag, version, outs = payload
        self.version = int(version)
        return [np.asarray(o) for o in outs]


class ServingClient:
    """Client for one :class:`~mxnet_tpu.serving.ServingReplica`."""

    def __init__(self, uri, window=None, connect_timeout=60.0):
        from ..kvstore import _ServerConn
        w = int(env("MXNET_SERVING_CLIENT_WINDOW", 64)
                if window is None else window)
        self.uri = str(uri)
        self._conn = _ServerConn(uri, connect_timeout=connect_timeout,
                                 window=max(1, w))

    def predict_async(self, data, name="data",
                      canary=False) -> PredictFuture:
        """Enqueue one predict; returns a :class:`PredictFuture`.  Many
        futures may be outstanding — that is exactly what feeds the
        replica's dynamic batcher.  ``canary=True`` sends the canary-
        tagged twin op: same batcher and reply shape, but counted
        separately on the replica (serving.canary_predict), so a fleet
        canary fraction is provable server-side."""
        payload = self._payload(data, name)
        if canary:
            return PredictFuture(
                self._conn.request(("predict_canary", payload)))
        return PredictFuture(self._conn.request(("predict", payload)))

    def predict(self, data, name="data"):
        """Blocking predict: returns the output list (np arrays, padded
        rows already sliced off by the replica)."""
        return self.predict_async(data, name=name).get()

    @staticmethod
    def _payload(data, name) -> Dict[str, np.ndarray]:
        if not isinstance(data, dict):
            data = {name: data}
        out = {}
        for k, v in data.items():
            arr = np.asarray(_raw(v))
            # ndim check BEFORE ascontiguousarray: the latter promotes
            # 0-d to 1-d and would mask a scalar input
            if arr.ndim < 1:
                raise MXNetError(f"predict input {k!r} needs a batch axis")
            out[str(k)] = np.ascontiguousarray(arr)
        return out

    def stats(self, timeout: Optional[float] = None) -> dict:
        """The replica's serving counters (version, queue depth,
        batches, shed count, draining flag, p50/p99/QPS latency dict,
        health verdict).  ``timeout`` bounds the wait — the fleet's
        scoreboard probe must not hang on a blackholed replica."""
        return _timed_await(self._conn.request(("serving_stats",)),
                            timeout, what="serving_stats")

    def refresh(self, timeout: Optional[float] = None) -> dict:
        """Force one weight-version check on the replica NOW; returns
        {version, refreshed, skipped}."""
        return _timed_await(self._conn.request(("serving_refresh",)),
                            timeout, what="serving_refresh")

    def drain(self, enable: bool = True,
              timeout: Optional[float] = None) -> dict:
        """Flip the replica's advisory drain flag (idempotent); returns
        ``{"draining": bool}``.  Routers observe it on the next stats
        poll; in-flight work still completes."""
        return _timed_await(self._conn.request(("drain", bool(enable))),
                            timeout, what="drain")

    def is_dead(self) -> bool:
        """Heartbeat silence past MXNET_KVSTORE_HEARTBEAT_TIMEOUT —
        the liveness half of a fleet scoreboard (a blackholed replica
        still acks heartbeats; only reply timeouts catch that)."""
        return self._conn.is_dead()

    def version(self) -> Optional[int]:
        return self.stats().get("version")

    def close(self):
        self._conn.close()

    def abort(self):
        """Abortive teardown for a gray-failed replica (accepting,
        heartbeating, never replying): fail the in-flight window NOW
        instead of draining — one swallowed reply has already
        misaligned this stream's FIFO acks for good, so the conn must
        be replaced, not reused (kvstore._ServerConn.abort)."""
        self._conn.abort()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
