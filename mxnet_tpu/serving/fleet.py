"""Fleet-grade serving: a health-routed replica-set client.

One :class:`FleetClient` over N :class:`~mxnet_tpu.serving.
ServingReplica`s makes replica death, degradation and overload
invisible to callers — the TF-Serving shape (arXiv:1605.08695: cheap
stateless routing over health-checked model servers with versioned
canary/rollback) built on the parameter-server transport this package
already made fault tolerant.

**Scoreboard.**  Every replica has a scoreboard entry fed by three
existing signals, none invented for routing:

* the transport heartbeat (``ServingClient.is_dead()`` — silence past
  ``MXNET_KVSTORE_HEARTBEAT_TIMEOUT``),
* the ``serving_stats`` reply's health verdict (OK/DEGRADED/CRITICAL
  with hysteresis, PR 12) + queue depth + draining flag, discounted by
  the verdict's wall-clock ``ts`` age (``health.discount_stale`` — a
  silent replica's last OK is not a live OK),
* per-request evidence: typed BUSY sheds, connection failures, and
  reply TIMEOUTS — the only signal that catches a gray-failed replica
  that accepts requests, acks heartbeats, and never answers.

A replica whose probe/attempt failed is QUARANTINED (ineligible) until
a scoreboard poll reaches it again — routing never waits on a corpse
to prove itself dead twice.

**Routing.**  Weighted least-loaded: score = (client in-flight +
replica queue depth + 1), multiplied by
``MXNET_SERVING_FLEET_DEGRADED_PENALTY`` for DEGRADED replicas (they
still serve, just less), ties broken round-robin.  CRITICAL, dead,
quarantined and draining replicas are excluded outright.

**Retries.**  Predict is PURE (the replica runs it outside the
exactly-once dedup window for the same reason), so a cross-replica
retry can never double-apply.  BusyError, connection failures and
reply timeouts retry against a DIFFERENT replica under a per-request
deadline (``MXNET_SERVING_FLEET_DEADLINE_S``) and retry budget
(``MXNET_SERVING_FLEET_RETRIES``) with capped, jittered exponential
backoff.  Budget exhaustion surfaces the LAST error, naming every
attempted replica.  The clock, sleep and RNG are injectable, so the
backoff schedule is testable without a single real sleep.

**Drain.**  ``drain(uri)`` sends the operator ``("drain",)`` envelope
and stops routing there (in-flight work completes);
``observe_roster(servers)`` reconciles against an observed membership
roster via :func:`mxnet_tpu.membership.roster_diff` — a departed uri
drains, a joined one becomes routable.

**Canary.**  ``start_canary([uri], fraction)`` refreshes the canary
cohort to the newly published weight version (serve N-1 while N warms)
and routes the configured fraction of requests there with the
canary-tagged predict op.  Every completed attempt lands a
(latency, ok) sample in its cohort's sliding window; once both cohorts
have ``MXNET_SERVING_FLEET_CANARY_MIN_N`` samples, a canary p99 above
baseline x ``_CANARY_P99_X`` — or a canary error rate above baseline x
``_CANARY_ERR_X`` (+1% absolute) — AUTO-ROLLS BACK: the canary cohort
drains, traffic returns to N-1, and the rollback lands in the health
flight recorder (``canary_rollback``) with both cohorts' numbers.
``promote_canary()`` is the happy path: refresh everyone to N.
"""
from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from ..analysis import hb as _hb
from ..base import MXNetError, env
from .. import health as _health
from .. import profiler as _prof
from ..membership import roster_diff
from .batcher import BusyError
from .client import PredictTimeout, ServingClient

#: scoreboard states (health verdicts plus the fleet-only lifecycle
#: states — DEAD covers heartbeat silence, quarantine and dial failure)
OK, DEGRADED, CRITICAL = "OK", "DEGRADED", "CRITICAL"
DEAD, DRAINING = "DEAD", "DRAINING"


class FleetError(MXNetError):
    """A fleet predict that exhausted its retry budget or deadline —
    the message names every attempted replica and carries the LAST
    underlying error (also chained as ``__cause__``)."""


class _Replica:
    """One scoreboard entry (mutated under FleetClient._lock)."""

    def __init__(self, uri: str):
        self.uri = uri
        self.client: Optional[ServingClient] = None
        self.inflight = 0          # this client's outstanding attempts
        self.routes = 0            # attempts routed here (lifetime)
        self.busy = 0              # BUSY sheds observed
        self.timeouts = 0          # reply timeouts observed
        self.conn_errors = 0       # dial/transport failures observed
        self.verdict = OK          # last health verdict (stale-discounted)
        self.verdict_age_s = None  # age of that verdict's ts stamp
        self.queue_depth = 0
        self.queue_limit = 1
        self.version = None
        self.draining = False      # operator/roster drain (no NEW work)
        self.remote_draining = False   # replica's own advisory flag,
        #                                synced (both ways) by the poll
        self.quarantined = False   # failed attempt/probe; poll clears
        self.canary = False        # member of the canary cohort

    def is_draining(self) -> bool:
        return self.draining or self.remote_draining

    def state(self) -> str:
        if self.quarantined or (self.client is not None
                                and self.client.is_dead()):
            return DEAD
        if self.is_draining():
            return DRAINING
        return self.verdict


class FleetClient:
    """Health-routed client over N serving replicas (module docstring
    has the full policy).  ``clock``/``sleep``/``rng`` are injectable
    for deterministic retry/backoff tests."""

    def __init__(self, uris: Sequence[str], window=None,
                 connect_timeout: float = 10.0, retries=None,
                 deadline_s=None, attempt_s=None, backoff_ms=None,
                 backoff_max_ms=None, jitter=None, stats_interval=None,
                 stale_s=None, degraded_penalty=None,
                 canary_fraction=None, canary_min_n=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        if not uris:
            raise MXNetError("a serving fleet needs at least one "
                             "replica uri")
        self._window = window
        self._connect_timeout = float(connect_timeout)
        self._retries = int(env("MXNET_SERVING_FLEET_RETRIES", 3)
                            if retries is None else retries)
        self._deadline_s = float(
            env("MXNET_SERVING_FLEET_DEADLINE_S", 30.0)
            if deadline_s is None else deadline_s)
        self._attempt_s = float(
            env("MXNET_SERVING_FLEET_ATTEMPT_S", 5.0)
            if attempt_s is None else attempt_s)
        self._backoff_s = float(
            env("MXNET_SERVING_FLEET_BACKOFF_MS", 10.0)
            if backoff_ms is None else backoff_ms) / 1000.0
        self._backoff_cap_s = float(
            env("MXNET_SERVING_FLEET_BACKOFF_MAX_MS", 500.0)
            if backoff_max_ms is None else backoff_max_ms) / 1000.0
        self._jitter = float(env("MXNET_SERVING_FLEET_JITTER", 0.5)
                             if jitter is None else jitter)
        self._stats_s = float(env("MXNET_SERVING_FLEET_STATS_S", 1.0)
                              if stats_interval is None
                              else stats_interval)
        self._stale_s = (None if stale_s is None else float(stale_s))
        self._penalty = float(
            env("MXNET_SERVING_FLEET_DEGRADED_PENALTY", 4.0)
            if degraded_penalty is None else degraded_penalty)
        self._canary_fraction = float(
            env("MXNET_SERVING_FLEET_CANARY_FRACTION", 0.1)
            if canary_fraction is None else canary_fraction)
        self._canary_min_n = int(
            env("MXNET_SERVING_FLEET_CANARY_MIN_N", 32)
            if canary_min_n is None else canary_min_n)
        self._canary_p99_x = float(
            env("MXNET_SERVING_FLEET_CANARY_P99_X", 2.0))
        self._canary_err_x = float(
            env("MXNET_SERVING_FLEET_CANARY_ERR_X", 2.0))
        self._clock = clock
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()
        # the scoreboard is read by every route() and mutated by the
        # poll loop + roster updates: identity in production, a
        # race-checked wrapper under the hb shim
        self._entries: Dict[str, _Replica] = _hb.track(
            {str(u): _Replica(str(u)) for u in uris},
            "fleet.HealthRoutedClient._entries")
        self._rr = 0               # round-robin tie-breaker
        self._canary_active = False
        self._cohorts = {c: {"lat": deque(maxlen=512), "n": 0, "err": 0}
                         for c in ("canary", "baseline")}
        self.last_rollback: Optional[dict] = None
        self._stop = threading.Event()
        self._poll_thread = None
        if self._stats_s > 0:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True)
            self._poll_thread.start()

    # -- scoreboard ----------------------------------------------------------
    def _client_for(self, entry: _Replica) -> ServingClient:
        """Dial lazily; a dial failure quarantines the entry (the poll
        loop re-probes) and surfaces as a retryable conn error."""
        with self._lock:
            if entry.client is not None:
                return entry.client
        client = ServingClient(entry.uri, window=self._window,
                               connect_timeout=self._connect_timeout)
        with self._lock:
            if entry.client is None:
                entry.client = client
                return client
        client.close()          # lost the race; one client per replica
        return entry.client

    def poll_once(self) -> dict:
        """One scoreboard sweep: every replica answers serving_stats
        (bounded by the per-attempt timeout) or gets quarantined.
        Returns {uri: state} after the sweep — the deterministic form
        of the background poll, and the only way a quarantined replica
        re-earns eligibility."""
        for entry in list(self._entries.values()):
            try:
                st = self._client_for(entry).stats(
                    timeout=self._attempt_s)
            except (MXNetError, ConnectionError, OSError):
                with self._lock:
                    entry.quarantined = True
                    poisoned, entry.client = entry.client, None
                if poisoned is not None:
                    try:
                        poisoned.abort()
                    except (MXNetError, OSError):
                        pass
                continue
            block = st.get("health")
            age = _health.verdict_age_s(block)
            verdict = (block or {}).get("status", OK)
            if verdict not in (OK, DEGRADED, CRITICAL):
                verdict = OK
            verdict = _health.discount_stale(verdict, age, self._stale_s)
            with self._lock:
                entry.quarantined = False
                entry.verdict = verdict
                entry.verdict_age_s = age
                entry.queue_depth = int(st.get("queue_depth", 0))
                entry.queue_limit = int(st.get("queue_limit", 1))
                entry.version = st.get("version")
                # the replica's own advisory drain flag: an operator
                # (possibly on ANOTHER fleet) drained or undrained it
                # directly — every poll observes the current truth
                entry.remote_draining = bool(st.get("draining"))
        return {u: e.state() for u, e in self._entries.items()}

    def _poll_loop(self):
        while not self._stop.wait(self._stats_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the poll must survive
                _prof.record_channel_event("fleet.poll_error")

    def scoreboard(self) -> dict:
        """{uri: entry dict} — the routing view, for operators and
        tests (states: OK/DEGRADED/CRITICAL/DEAD/DRAINING)."""
        with self._lock:
            return {u: {
                "state": e.state(),
                "verdict": e.verdict,
                "verdict_age_s": e.verdict_age_s,
                "queue_depth": e.queue_depth,
                "inflight": e.inflight,
                "routes": e.routes,
                "busy": e.busy,
                "timeouts": e.timeouts,
                "conn_errors": e.conn_errors,
                "draining": e.is_draining(),
                "quarantined": e.quarantined,
                "canary": e.canary,
                "version": e.version,
            } for u, e in self._entries.items()}

    # -- routing -------------------------------------------------------------
    def _eligible(self, cohort: Optional[str]) -> List[_Replica]:
        """Routable replicas (caller holds _lock): never CRITICAL,
        dead, quarantined or draining; restricted to the request's
        cohort while a canary is active and the cohort has survivors."""
        out = []
        for e in self._entries.values():
            st = e.state()
            if st in (DEAD, DRAINING, CRITICAL):
                continue
            out.append(e)
        if cohort is not None:
            want = cohort == "canary"
            cohort_live = [e for e in out if e.canary == want]
            if cohort_live:
                return cohort_live
            # the whole cohort is sick: availability beats the split —
            # fall through to anyone eligible
        return out

    def _route(self, exclude, cohort: Optional[str]) -> _Replica:
        """Weighted-least-loaded pick.  ``exclude`` holds the uris this
        request already failed on — preferred away from, but allowed
        again when they are the only survivors (a retry against the
        same replica still beats a guaranteed failure)."""
        with self._lock:
            cands = self._eligible(cohort)
            fresh = [e for e in cands if e.uri not in exclude]
            pool = fresh or cands
            if not pool:
                raise FleetError(
                    "no eligible serving replica (states: %s)"
                    % {u: e.state() for u, e in self._entries.items()})

            def score(e):
                s = float(e.inflight + e.queue_depth + 1)
                if e.verdict == DEGRADED:
                    s *= self._penalty
                return s

            best = min(score(e) for e in pool)
            tied = [e for e in pool if score(e) == best]
            self._rr += 1
            entry = tied[self._rr % len(tied)]
            entry.inflight += 1
            entry.routes += 1
        _prof.record_channel_event("fleet.route")
        _prof.record_channel_event("fleet.route:%s" % entry.uri)
        return entry

    # -- the request path ----------------------------------------------------
    def predict(self, data, name: str = "data"):
        """Routed, retried, deadline-bounded predict; returns the
        output list.  BusyError / connection failure / reply timeout
        retries on a different replica (predict is pure); budget or
        deadline exhaustion raises :class:`FleetError` naming every
        attempted replica with the LAST error chained."""
        deadline = self._clock() + self._deadline_s
        cohort = None
        if self._canary_active:
            cohort = ("canary"
                      if self._rng.random() < self._canary_fraction
                      else "baseline")
        attempted: List[str] = []
        last_exc: Optional[BaseException] = None
        attempt = 0
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                raise self._exhausted("deadline %.3fs" % self._deadline_s,
                                      attempted, last_exc)
            try:
                entry = self._route(set(attempted), cohort)
            except FleetError:
                if not attempted:
                    raise      # nothing routable from the start
                # mid-retry the pool dried up (e.g. the last survivor
                # was just quarantined): still name the attempts and
                # chain what actually went wrong
                raise self._exhausted("eligible-replica pool",
                                      attempted, last_exc)
            sample_cohort = ("canary" if entry.canary else "baseline") \
                if self._canary_active else None
            t0 = self._clock()
            try:
                fut = self._client_for(entry).predict_async(
                    data, name=name, canary=entry.canary)
                outs = fut.get(timeout=min(self._attempt_s, remaining))
            except BusyError as exc:
                self._attempt_failed(entry, exc, sample_cohort, t0)
            except PredictTimeout as exc:
                self._attempt_failed(entry, exc, sample_cohort, t0,
                                     quarantine=True)
            except (MXNetError, ConnectionError, OSError) as exc:
                self._attempt_failed(entry, exc, sample_cohort, t0,
                                     quarantine=True)
            else:
                dur = self._clock() - t0
                with self._lock:
                    entry.inflight -= 1
                _prof.record_latency("fleet.request", dur)
                if sample_cohort is not None:
                    self._note_sample(sample_cohort, dur, ok=True)
                return outs
            last_exc = self._last_exc
            attempted.append(entry.uri)
            attempt += 1
            if attempt > self._retries:
                raise self._exhausted(
                    "retry budget (%d retries)" % self._retries,
                    attempted, last_exc)
            # capped exponential backoff with jitter, never past the
            # deadline; with jitter=0 and an injected clock the sleep
            # schedule is EXACTLY base * 2^k capped — what the
            # determinism tests pin
            delay = min(self._backoff_s * (2.0 ** (attempt - 1)),
                        self._backoff_cap_s)
            if self._jitter > 0:
                delay *= 1.0 + self._jitter * (2.0 * self._rng.random()
                                               - 1.0)
            delay = max(0.0, min(delay, deadline - self._clock()))
            _prof.record_channel_event("fleet.retry")
            if delay > 0:
                self._sleep(delay)

    def _attempt_failed(self, entry: _Replica, exc, sample_cohort, t0,
                        quarantine: bool = False):
        dur = self._clock() - t0
        poisoned = None
        with self._lock:
            entry.inflight -= 1
            if isinstance(exc, BusyError):
                entry.busy += 1
            elif isinstance(exc, PredictTimeout):
                entry.timeouts += 1
            else:
                entry.conn_errors += 1
            if quarantine and not entry.quarantined:
                entry.quarantined = True
                # a conn that timed out or faulted is suspect for good:
                # a swallowed reply misaligns its FIFO ack window, so
                # REPLACE it — the probe that lifts the quarantine
                # re-dials fresh (ServingClient.abort docstring)
                poisoned, entry.client = entry.client, None
                _health.note("fleet_quarantine", uri=entry.uri,
                             error=type(exc).__name__)
        if poisoned is not None:
            try:
                poisoned.abort()
            except (MXNetError, OSError):
                pass
        kind = ("fleet.busy" if isinstance(exc, BusyError)
                else "fleet.timeout" if isinstance(exc, PredictTimeout)
                else "fleet.conn_error")
        _prof.record_channel_event(kind)
        if sample_cohort is not None:
            self._note_sample(sample_cohort, dur, ok=False)
        self._last_exc = exc

    def _exhausted(self, what: str, attempted: List[str],
                   last_exc) -> FleetError:
        tried = ", ".join(attempted) or "<none>"
        if last_exc is None:
            return FleetError(
                f"fleet predict exhausted its {what} before any "
                f"replica could be attempted (tried: {tried})")
        err = FleetError(
            f"fleet predict exhausted its {what} after "
            f"{len(attempted)} attempt(s) across replicas [{tried}]; "
            f"last error from {attempted[-1]}: "
            f"{type(last_exc).__name__}: {last_exc}")
        err.__cause__ = last_exc
        return err

    # -- drain / roster observation ------------------------------------------
    def drain(self, uri: str, wire: bool = True,
              timeout: Optional[float] = None) -> None:
        """Operator drain: stop routing NEW work to ``uri`` (in-flight
        completes).  ``wire=True`` also flips the replica's advisory
        drain flag so every other fleet observes it on its next poll."""
        entry = self._require(uri)
        with self._lock:
            entry.draining = True
        _prof.record_channel_event("fleet.drain")
        _health.note("fleet_drain", uri=uri)
        if wire:
            try:
                self._client_for(entry).drain(
                    True, timeout=timeout or self._attempt_s)
            except (MXNetError, ConnectionError, OSError):
                pass   # the local exclusion already holds

    def undrain(self, uri: str, wire: bool = True,
                timeout: Optional[float] = None) -> None:
        """Return a drained replica to the routable pool."""
        entry = self._require(uri)
        with self._lock:
            entry.draining = False
        _prof.record_channel_event("fleet.undrain")
        if wire:
            try:
                self._client_for(entry).drain(
                    False, timeout=timeout or self._attempt_s)
            except (MXNetError, ConnectionError, OSError):
                pass

    def observe_roster(self, servers: Sequence[str]) -> dict:
        """Reconcile the fleet against an observed membership roster
        (:func:`membership.roster_diff`): a uri that LEFT the roster is
        drained (no wire op — it is leaving or gone), a new one becomes
        a routable entry.  Returns {"added": [...], "removed": [...]}."""
        with self._lock:
            current = [u for u, e in self._entries.items()
                       if not e.draining]
        added, removed = roster_diff(current, servers)
        for uri in removed:
            entry = self._entries.get(uri)
            if entry is not None:
                with self._lock:
                    entry.draining = True
                _prof.record_channel_event("fleet.drain")
                _health.note("fleet_drain", uri=uri,
                             reason="roster_departure")
        for uri in added:
            with self._lock:
                if uri not in self._entries:
                    self._entries[uri] = _Replica(uri)
        return {"added": added, "removed": removed}

    def _require(self, uri: str) -> _Replica:
        entry = self._entries.get(str(uri))
        if entry is None:
            raise MXNetError(f"replica {uri!r} is not part of this "
                             f"fleet: {sorted(self._entries)}")
        return entry

    # -- canary / rollback ---------------------------------------------------
    @property
    def canary_active(self) -> bool:
        return self._canary_active

    def start_canary(self, uris: Sequence[str], fraction=None,
                     refresh: bool = True,
                     timeout: Optional[float] = None) -> dict:
        """Designate ``uris`` as the canary cohort and (by default)
        force their weight refresh NOW, so they serve the newly
        published version N while the baseline keeps N-1.  The
        configured fraction of requests routes to the cohort with the
        canary-tagged predict op; both cohorts' SLO windows restart
        empty.  Returns {uri: refresh reply | None}."""
        uris = [str(u) for u in uris]
        for u in uris:
            self._require(u)
        if fraction is not None:
            self._canary_fraction = float(fraction)
        replies = {}
        for u in uris:
            entry = self._entries[u]
            if refresh:
                replies[u] = self._client_for(entry).refresh(
                    timeout=timeout or self._attempt_s)
            else:
                replies[u] = None
        with self._lock:
            for e in self._entries.values():
                e.canary = e.uri in uris
            for c in self._cohorts.values():
                c["lat"].clear()
                c["n"] = 0
                c["err"] = 0
            self._canary_active = True
            self.last_rollback = None
        _prof.record_channel_event("fleet.canary_start")
        _health.note("canary_start", uris=uris,
                     fraction=self._canary_fraction)
        return replies

    def _note_sample(self, cohort: str, dur_s: float, ok: bool):
        with self._lock:
            if not self._canary_active:
                return
            c = self._cohorts[cohort]
            c["n"] += 1
            if ok:
                c["lat"].append(float(dur_s))
            else:
                c["err"] += 1
            regression = (cohort == "canary"
                          and self._canary_regressed())
        if regression:
            self._rollback()

    def _canary_regressed(self) -> Optional[dict]:
        """Caller holds _lock.  The SLO comparison: canary vs baseline
        cohort, only once BOTH have the minimum sample count."""
        can, base = self._cohorts["canary"], self._cohorts["baseline"]
        if can["n"] < self._canary_min_n or \
                base["n"] < self._canary_min_n:
            return None
        can_err = can["err"] / can["n"]
        base_err = base["err"] / base["n"]
        can_p99 = _p99(can["lat"])
        base_p99 = _p99(base["lat"])
        reasons = []
        if can_err > base_err * self._canary_err_x + 0.01:
            reasons.append("error_rate")
        if base_p99 is not None and can_p99 is not None \
                and can_p99 > base_p99 * self._canary_p99_x:
            reasons.append("p99")
        if not reasons:
            return None
        return {"reasons": reasons,
                "canary_p99_ms": _ms(can_p99),
                "baseline_p99_ms": _ms(base_p99),
                "canary_err_rate": round(can_err, 4),
                "baseline_err_rate": round(base_err, 4),
                "canary_n": can["n"], "baseline_n": base["n"]}

    def _rollback(self):
        """Auto-rollback: drain the canary cohort, return all traffic
        to the N-1 baseline, and put the event on the flight recorder
        with both cohorts' numbers — the forensics a paged operator
        reads first."""
        with self._lock:
            detail = self._canary_regressed()
            if not self._canary_active or detail is None:
                return
            self._canary_active = False
            self.last_rollback = detail
            rolled = [e.uri for e in self._entries.values() if e.canary]
            for e in self._entries.values():
                if e.canary:
                    e.draining = True
                    e.canary = False
        _prof.record_channel_event("fleet.rollback")
        _health.note("canary_rollback", uris=rolled, **detail)

    def promote_canary(self, timeout: Optional[float] = None,
                       refresh: bool = True) -> dict:
        """The canary held: refresh every baseline replica to the new
        version and dissolve the cohorts.  Returns {uri: refresh
        reply}.  ``refresh=False`` skips the wire refresh (mirroring
        ``start_canary`` — for fleets whose replicas pick the version
        up on their own poll, or have no parameter servers to pull
        from) and only dissolves the cohorts."""
        with self._lock:
            if not self._canary_active:
                raise MXNetError("no active canary to promote")
            baseline = [e.uri for e in self._entries.values()
                        if not e.canary]
        replies = {}
        if refresh:
            for u in baseline:
                entry = self._entries[u]
                replies[u] = self._client_for(entry).refresh(
                    timeout=timeout or self._attempt_s)
        with self._lock:
            for e in self._entries.values():
                e.canary = False
            self._canary_active = False
        _prof.record_channel_event("fleet.canary_promote")
        _health.note("canary_promote", uris=baseline)
        return replies

    def canary_report(self) -> dict:
        """Both cohorts' live SLO numbers (tests and operators)."""
        with self._lock:
            out = {}
            for name, c in self._cohorts.items():
                out[name] = {
                    "n": c["n"], "err": c["err"],
                    "err_rate": round(c["err"] / c["n"], 4)
                    if c["n"] else 0.0,
                    "p99_ms": _ms(_p99(c["lat"]))}
            out["active"] = self._canary_active
            out["last_rollback"] = self.last_rollback
            return out

    # -- lifecycle -----------------------------------------------------------
    def close(self):
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10.0)
        for entry in self._entries.values():
            client = entry.client
            if client is None:
                continue
            try:
                if entry.quarantined or client.is_dead():
                    client.abort()    # never drain against a corpse
                else:
                    client.close()
            except (MXNetError, OSError):
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _p99(samples) -> Optional[float]:
    vals = sorted(samples)
    if not vals:
        return None
    return vals[min(len(vals) - 1, int(0.99 * (len(vals) - 1)))]


def _ms(v) -> Optional[float]:
    return None if v is None else round(v * 1000.0, 3)
