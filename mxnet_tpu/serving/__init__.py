"""mxnet_tpu.serving — production inference on the hardened kvstore wire.

The transport PRs 2–5 hardened for gradients (zero-copy tensor frames,
sliding-window pipelining, reconnect + exactly-once replay, allowlisted
decode, TCP_NODELAY) carries inference traffic unchanged; this package
adds the server shape on top (TF-Serving, arXiv:1605.08695, rebuilt on
this codebase's idioms):

* :class:`BucketedPredictor` — a checkpoint loaded into bucketed
  pre-compiled predict executables (pad-to-bucket batch shapes: N
  request sizes never mean N compiles).
* :class:`DynamicBatcher` — continuous batching: a request queue drains
  into the largest ready bucket under ``MXNET_SERVING_MAX_WAIT_MS``,
  with queue-depth admission control shedding overload as a typed BUSY
  reply (:class:`BusyError` client-side).
* :class:`ServingReplica` — a :class:`~mxnet_tpu.kvstore_server.
  KVStoreServer` subclass serving ``predict`` / ``serving_stats`` /
  ``serving_refresh`` envelopes over pipelined connections, and hot-
  swapping weights ``pull()``-ed from live dist_async parameter servers
  on a version bump — train and serve from one parameter-server
  cluster.
* :class:`ServingClient` — pipelined client riding the kvstore channel
  (reconnect/replay and heartbeats included).
* :class:`FleetClient` — a health-routed replica-set client over N
  replicas: scoreboard-driven weighted-least-loaded routing, cross-
  replica retries under a deadline + retry budget (predict is pure),
  operator/roster drain, and versioned canary rollout with automatic
  SLO rollback.  Replica death, degradation and overload stop being the
  caller's problem.

Latency SLOs are first-class: every request records into
``profiler.record_latency``; ``profiler.latency_stats("serving.
request")`` exposes p50/p99/QPS next to ``wire_bytes_per_step``.

See docs/SERVING.md for architecture, knobs and the train-and-serve
topology.
"""
from .bucketed import BucketedPredictor, parse_buckets
from .batcher import BusyError, DynamicBatcher
from .replica import ServingReplica, VERSION_KEY
from .client import PredictFuture, PredictTimeout, ServingClient
from .fleet import FleetClient, FleetError

__all__ = [
    "BucketedPredictor", "BusyError", "DynamicBatcher", "FleetClient",
    "FleetError", "PredictFuture", "PredictTimeout", "ServingClient",
    "ServingReplica", "VERSION_KEY", "parse_buckets", "publish_version",
]


def publish_version(kv, version=None):
    """Publish a serving weight version to the parameter servers the
    replicas watch.  Call AFTER the weights on the servers are the ones
    to serve (dist_async update-on-kvstore keeps them current by
    construction); replicas refresh on the next poll tick or
    ``serving_refresh`` envelope.

    ``version=None`` increments the currently-published version (single
    publisher — the trainer).  The counter rides :meth:`KVStore.assign`
    (updater-bypassing), never ``push``: a version bump must not be
    \"applied\" as a gradient."""
    import jax.numpy as jnp
    from ..base import MXNetError
    from ..ndarray import NDArray
    if version is None:
        out = NDArray(jnp.zeros((1,), jnp.float64))
        try:
            kv.pull(VERSION_KEY, out=out)
            current = int(round(float(out.asnumpy()[0])))
        except MXNetError:
            current = 0
        version = current + 1
    kv.assign(VERSION_KEY,
              NDArray(jnp.asarray([float(version)], jnp.float64)))
    return int(version)
