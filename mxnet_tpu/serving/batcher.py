"""Dynamic (continuous) request batcher with queue-depth admission control.

The TF-Serving batching shape (arXiv:1605.08695 §4) on this package's
threading idioms: requests enqueue as reply slots; ONE worker thread
drains the queue into the largest ready bucket — it dispatches the
moment the queued rows fill the biggest configured bucket, or when the
OLDEST queued request has waited ``MXNET_SERVING_MAX_WAIT_MS``,
whichever is first.  Admission control is a queue-depth dial
(``MXNET_SERVING_QUEUE_DEPTH``): requests past the limit complete
immediately with a typed BUSY reply instead of growing an unbounded
queue — shedding is the SLO-preserving answer to overload, and the
client surfaces it as :class:`BusyError`, distinct from every real
error.

Crash propagation follows the package's sticky-error thread contract
(PrefetchingIter, _ServerConn._io_loop): a worker crash parks the error,
fails every queued slot and every later submit loudly — a reply slot is
never silently abandoned.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List

import numpy as np

from ..base import MXNetError, env
from .. import profiler as _prof
from .. import tracing as _tr
from .. import health as _health
from .bucketed import _raw


class BusyError(MXNetError):
    """Typed overload signal: the replica shed this request at admission
    (queue depth past ``MXNET_SERVING_QUEUE_DEPTH``).  Retry with
    backoff or route to another replica — the model was never run."""


class _ReplySlot:
    """One request's reply rendezvous: ``reply`` is the transport-level
    ``("ok"|"err", payload)`` tuple the connection writer sends when
    ``done`` fires."""

    __slots__ = ("done", "reply", "data", "n", "t_enqueue", "sig", "role",
                 "span")

    def __init__(self, data=None, n=0, sig=None):
        self.done = threading.Event()
        self.reply = None
        self.data = data
        self.n = n
        self.sig = sig
        self.role = None     # fault-injection tag set by the conn loop
        self.span = None     # detached srv.predict span (replica._admit)
        self.t_enqueue = time.monotonic()

    def complete(self, reply):
        self.reply = reply
        self.done.set()


class DynamicBatcher:
    """Drain a request queue into bucketed predict dispatches."""

    def __init__(self, predictor, max_wait_s=None, queue_depth=None):
        self._predictor = predictor
        self._max_wait = float(
            env("MXNET_SERVING_MAX_WAIT_MS", 2.0) / 1000.0
            if max_wait_s is None else max_wait_s)
        self._queue_depth = int(env("MXNET_SERVING_QUEUE_DEPTH", 256)
                                if queue_depth is None else queue_depth)
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._stop = False
        self._err = None
        self.batches = 0          # dispatches issued
        self.shed = 0             # requests answered BUSY
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- intake --------------------------------------------------------------
    def submit(self, data, span=None) -> _ReplySlot:
        """Admit one request; ALWAYS returns a slot (completed on the
        spot for BUSY/validation failures — the caller just forwards the
        reply).  ``span`` (a detached tracing span, replica._admit)
        must ride in HERE, before the slot is queued: attaching it
        after submit would race the batcher thread, which annotates
        the span with the request's queue wait at dispatch."""
        slot = _ReplySlot()
        slot.span = span
        try:
            datas, n, sig = self._validate(data)
        except MXNetError as exc:
            slot.complete(("err", f"{type(exc).__name__}: {exc}"))
            return slot
        slot.data, slot.n, slot.sig = datas, n, sig
        with self._cv:
            if self._err is not None:
                slot.complete(("err", "serving batcher failed: "
                               f"{self._err}"))
                return slot
            if self._stop:
                slot.complete(("err", "serving replica is stopping"))
                return slot
            if len(self._q) >= self._queue_depth:
                # the admission dial: shed NOW with a typed BUSY reply —
                # never queue unboundedly (the p99 killer)
                self.shed += 1
                _prof.record_channel_event("serving.busy_shed")
                # the health rule engine counts these in a sliding
                # window: >= MXNET_HEALTH_BUSY_STORM sheds within
                # MXNET_HEALTH_BUSY_WINDOW_S flips the replica to
                # DEGRADED (recovering with hysteresis)
                _health.note("busy_shed")
                slot.complete(("ok", ("busy", {
                    "queue_depth": len(self._q),
                    "limit": self._queue_depth})))
                return slot
            self._q.append(slot)
            self._cv.notify_all()
        return slot

    def _validate(self, data):
        if not isinstance(data, dict):
            raise MXNetError("predict payload must be a {name: array} "
                             f"dict, got {type(data).__name__}")
        datas: Dict[str, np.ndarray] = {}
        n = None
        for name, v in data.items():
            arr = np.asarray(_raw(v))
            if arr.ndim < 1:
                raise MXNetError(f"predict input {name!r} needs a batch "
                                 "axis")
            if n is None:
                n = int(arr.shape[0])
            elif int(arr.shape[0]) != n:
                raise MXNetError("predict inputs disagree on the row "
                                 "count")
            datas[str(name)] = arr
        if not datas or not n:
            raise MXNetError("empty predict payload")
        # the coalescing signature: only same-structure requests share a
        # padded bucket (names + feature shapes + dtypes)
        sig = tuple(sorted((name, tuple(a.shape[1:]), str(a.dtype))
                           for name, a in datas.items()))
        return datas, n, sig

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._q)

    @property
    def queue_limit(self) -> int:
        return self._queue_depth

    # -- worker --------------------------------------------------------------
    def _loop(self):
        try:
            while True:
                batch = self._collect()
                if batch is None:
                    return
                self._dispatch(batch)
        except Exception as exc:  # noqa: BLE001 — sticky-error contract
            with self._cv:
                self._err = exc
                failed, self._q = list(self._q), deque()
            for slot in failed:
                slot.complete(("err", f"serving batcher failed: {exc}"))

    def _collect(self):
        """Block for work, then drain until the largest bucket is full
        or the oldest request's max-wait expires; returns the slots of
        ONE dispatch (same structure signature), or None on stop.

        Only slots sharing the HEAD's structure signature count toward
        (and join) the dispatch — but the scan covers the WHOLE queue,
        not just a contiguous prefix, so interleaved traffic from
        clients with different input structures still coalesces instead
        of degrading to batches of one.  Skipped slots keep their queue
        order and their (older) enqueue times, so the next collect's
        max-wait deadline fires for them immediately."""
        max_rows = self._predictor.buckets[-1]
        with self._cv:
            while not self._q:
                if self._stop:
                    return None
                self._cv.wait(0.1)
            head_sig = self._q[0].sig
            deadline = self._q[0].t_enqueue + self._max_wait
            while not self._stop:
                rows = sum(s.n for s in self._q if s.sig == head_sig)
                if rows >= max_rows:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
            taken: List[_ReplySlot] = []
            kept: deque = deque()
            rows = 0
            while self._q:
                slot = self._q.popleft()
                if (slot.sig == head_sig
                        and (not taken or rows + slot.n <= max_rows)):
                    # the head always dispatches, even oversize (the
                    # predictor chunks it through the largest bucket)
                    taken.append(slot)
                    rows += slot.n
                else:
                    kept.append(slot)
            self._q = kept
        return taken

    def _dispatch(self, slots):
        data = {name: np.concatenate([s.data[name] for s in slots], axis=0)
                for name in slots[0].data}
        t_batch = time.monotonic()
        # the DEVICE half of a request's latency: queue-wait is
        # (t_batch - slot.t_enqueue) per slot, everything inside this
        # span is padded forward + readback.  Each parked slot's
        # detached srv.predict span (replica._admit) spans the whole
        # stay, so on the merged timeline queue time and device time
        # separate per request (docs/OBSERVABILITY.md)
        bsp = _tr.span_begin(
            "serving.batch", cat="serving", detach=True,
            args={"rows": int(sum(s.n for s in slots)),
                  "slots": len(slots),
                  "queue_wait_ms_max": round(
                      (t_batch - min(s.t_enqueue for s in slots)) * 1e3,
                      3)})
        try:
            version, outs = self._predictor.predict(data)
        except Exception as exc:  # noqa: BLE001 — fail THIS batch only
            _tr.span_end(bsp, args={"error": type(exc).__name__})
            for slot in slots:
                slot.complete(("err", f"{type(exc).__name__}: {exc}"))
            return
        _tr.span_end(bsp)
        self.batches += 1
        lo = 0
        now = time.monotonic()
        for slot in slots:
            hi = lo + slot.n
            if slot.span is not None:
                slot.span.args = dict(
                    slot.span.args or {},
                    queue_wait_ms=round((t_batch - slot.t_enqueue) * 1e3,
                                        3))
            slot.complete(("ok", ("result", version,
                                  [o[lo:hi] for o in outs])))
            # end-to-end request latency (queue wait + padded forward +
            # readback): the p50/p99/QPS the profiler serves
            _prof.record_latency("serving.request",
                                 now - slot.t_enqueue, ts=now)
            lo = hi

    def stop(self):
        """Stop the worker; fail everything still queued."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=10.0)
        with self._cv:
            leftover, self._q = list(self._q), deque()
        for slot in leftover:
            slot.complete(("err", "serving replica is stopping"))
