"""Deterministic fault injection for the dist kvstore transport.

The reference's ps-lite layer survives transient transport faults
(kvstore_dist.h:55 server-recovery mode); proving the same property here
needs faults that happen ON DEMAND, at an exact message, every run.  This
module is that switchboard: the kvstore client transport
(``kvstore._ServerConn``) and server (``kvstore_server``) call the hooks
below from ``_send_msg`` / ``_recv_msg`` / the accept loop, and a test —
or an env-configured worker process — arms a plan:

* **kill the connection** when the Nth data-channel message is about to
  be sent (``before_send``), has just been sent (``after_send`` — the
  request reached the server but its ack will be lost, so the replay
  must be deduped), or while awaiting its ack (``on_recv``);
* **delay acks** server-side (widens race windows deterministically);
* **refuse connects** client-side and/or **drop accepts** server-side
  (exercises connect/reconnect backoff);
* **kill the process** after exactly N enveloped replies
  (``kill_process_after_acks``) or at beat number N of the elastic beat
  loop (``kill_on_beat_seq``) — REAL SIGKILL, the preemption shape the
  elastic membership and coordinator-failover machinery must survive;
  target one server id (``MXNET_FI_ONLY_SERVER``) and/or the process
  currently holding the COORDINATOR role
  (``MXNET_FI_ONLY_COORDINATOR``, kept current across failovers by
  ``note_coordinator``).

Heartbeat channels are exempt (the hooks are only called with
``fi_role`` set on DATA-channel traffic), so a plan severs exactly the
request/reply stream the test targets.

Context managers for in-process tests::

    with faultinject.kill_connection_after(3, point="after_send"):
        kv.push("w", grad)          # 3rd message dies post-send
        kv.pull("w", out=out)       # reconnect + replay, exactly-once

Env activation for multi-process tests (read once at import; see
``tests/dist/dist_fault_injection.py``)::

    MXNET_FI_KILL_AFTER=5 MXNET_FI_KILL_POINT=after_send \
    MXNET_FI_ONLY_RANK=0  python tools/launch.py -n 2 -s 1 ...

All state is process-global and lock-guarded; ``reset()`` disarms
everything.  No plan armed = every hook is a cheap no-op.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time

KILL_POINTS = ("before_send", "after_send", "on_recv")

_lock = threading.RLock()


class _Plan:
    """The armed fault plan + its counters (guarded by _lock)."""

    def __init__(self):
        self.kill_after = None          # 1-indexed message to kill at
        self.kill_point = "before_send"
        self.kill_unacked = None        # sever when k envelopes in flight
        self.sent = 0                   # data-channel messages counted
        self.kills_fired = 0
        self.delay_ack_s = 0.0
        self.refuse_connects = 0        # remaining connects to refuse
        self.connects_refused = 0
        self.refuse_accepts = 0         # remaining accepts to drop
        self.accepts_refused = 0
        self.only_rank = None           # limit the plan to one worker rank
        self.kill_process_after = None  # SIGKILL self after n served acks
        self.acks_served = 0            # enveloped replies counted
        self.only_server = None         # limit process kill to one server id
        self.only_coordinator = False   # limit process kill to the
        #                                 CURRENT roster coordinator
        self.kill_on_beat_seq = None    # SIGKILL self at beat number n
        self.stall_barrier_s = 0.0      # injected barrier-arrival delay
        self.stall_barrier_times = 0    # remaining stalls to inject
        self.blackhole_after = None     # go reply-silent after n replies
        self.bh_seen = 0                # server replies counted
        self.blackholed = 0             # replies swallowed
        self.shm_wedge_after = None     # stop draining the shm ring
        #                                 after n popped frames
        self.shm_drained = 0            # lane frames drained so far
        self.shm_wedged = 0             # drains swallowed by the wedge


_plan = _Plan()

# Whether THIS process currently holds the elastic roster COORDINATOR
# role.  kvstore_server keeps it current (ctor role, every beat tick,
# and at failover promotion), so MXNET_FI_ONLY_COORDINATOR plans track
# the role across a succession instead of a fixed server id.
_is_coordinator = False


def note_coordinator(flag: bool) -> None:
    """Record whether this process is the roster coordinator right now
    (called by kvstore_server; the ONLY_COORDINATOR filter reads it)."""
    global _is_coordinator
    _is_coordinator = bool(flag)


def _rank_active():
    if _plan.only_rank is None:
        return True
    return os.environ.get("DMLC_WORKER_ID", "0") == str(_plan.only_rank)


def _server_active():
    if _plan.only_server is not None and \
            os.environ.get("DMLC_SERVER_ID", "0") != str(_plan.only_server):
        return False
    if _plan.only_coordinator and not _is_coordinator:
        return False
    return True


def reset():
    """Disarm every fault and zero the counters."""
    global _plan
    with _lock:
        _plan = _Plan()


def stats() -> dict:
    """Counters for test assertions (kills fired, refusals served)."""
    with _lock:
        return {"kills_fired": _plan.kills_fired,
                "connects_refused": _plan.connects_refused,
                "accepts_refused": _plan.accepts_refused,
                "messages_seen": _plan.sent,
                "acks_served": _plan.acks_served,
                "replies_blackholed": _plan.blackholed,
                "shm_frames_wedged": _plan.shm_wedged}


def configure(kill_after=None, kill_point="before_send", delay_ack_s=0.0,
              refuse_connects=0, refuse_accepts=0, only_rank=None,
              kill_unacked=None, kill_process_after=None, only_server=None,
              only_coordinator=False, kill_on_beat_seq=None,
              stall_barrier_s=0.0, stall_barrier_times=1,
              blackhole_after=None, shm_wedge_after=None):
    """Arm a plan directly (the non-context-manager form; multi-process
    scripts use this after deciding per-rank what to inject)."""
    if kill_point not in KILL_POINTS:
        raise ValueError(f"kill_point must be one of {KILL_POINTS}, "
                         f"got {kill_point!r}")
    with _lock:
        _plan.kill_after = int(kill_after) if kill_after else None
        _plan.kill_point = kill_point
        _plan.kill_unacked = int(kill_unacked) if kill_unacked else None
        _plan.sent = 0
        _plan.kills_fired = 0
        _plan.delay_ack_s = float(delay_ack_s)
        _plan.refuse_connects = int(refuse_connects)
        _plan.connects_refused = 0
        _plan.refuse_accepts = int(refuse_accepts)
        _plan.accepts_refused = 0
        _plan.only_rank = only_rank
        _plan.kill_process_after = (int(kill_process_after)
                                    if kill_process_after else None)
        _plan.acks_served = 0
        _plan.only_server = only_server
        _plan.only_coordinator = bool(only_coordinator)
        _plan.kill_on_beat_seq = (int(kill_on_beat_seq)
                                  if kill_on_beat_seq else None)
        _plan.stall_barrier_s = float(stall_barrier_s)
        _plan.stall_barrier_times = (int(stall_barrier_times)
                                     if stall_barrier_s > 0 else 0)
        _plan.blackhole_after = (int(blackhole_after)
                                 if blackhole_after is not None else None)
        _plan.bh_seen = 0
        _plan.blackholed = 0
        _plan.shm_wedge_after = (int(shm_wedge_after)
                                 if shm_wedge_after is not None else None)
        _plan.shm_drained = 0
        _plan.shm_wedged = 0


@contextlib.contextmanager
def kill_connection_after(n, point="before_send"):
    """Sever the data channel at the Nth message (1-indexed), once."""
    if point not in KILL_POINTS:
        raise ValueError(f"point must be one of {KILL_POINTS}, got {point!r}")
    with _lock:
        _plan.kill_after = int(n)
        _plan.kill_point = point
        _plan.sent = 0
        _plan.kills_fired = 0
    try:
        yield
    finally:
        with _lock:
            _plan.kill_after = None
            _plan.sent = 0


@contextlib.contextmanager
def kill_when_unacked(k):
    """Sever the data channel the first time ``k`` envelopes are in
    flight (sent, unacked) at once — the mid-WINDOW kill for the
    pipelined transport: the reconnect must replay all ``k`` in seq
    order, exactly-once."""
    with _lock:
        _plan.kill_unacked = int(k)
    try:
        yield
    finally:
        with _lock:
            _plan.kill_unacked = None


@contextlib.contextmanager
def kill_process_after_acks(n):
    """SIGKILL THIS PROCESS the moment it has served ``n`` enveloped
    data-channel replies — REAL process death (no atexit, no socket
    shutdown handshake, no Python unwind), the preemption shape the
    elastic-membership machinery must survive.  Heartbeat pings and raw
    messages are exempt, so the count is deterministic: it advances
    only on the exactly-once request stream.  Env form:
    ``MXNET_FI_KILL_PROCESS_AFTER`` (+ ``MXNET_FI_ONLY_SERVER`` to
    target one DMLC_SERVER_ID in a launcher-spawned job)."""
    with _lock:
        _plan.kill_process_after = int(n)
        _plan.acks_served = 0
    try:
        yield
    finally:
        with _lock:
            _plan.kill_process_after = None


@contextlib.contextmanager
def kill_on_beat_seq(n):
    """SIGKILL THIS PROCESS when its elastic beat loop sends beat number
    ``n`` — the deterministic BEAT-boundary kill point.  The enveloped-
    ack count (``kill_process_after_acks``) is the right dial for a
    data-shard server, but the COORDINATOR also serves barrier
    rendezvous and roster ops whose ack ordering is timing-dependent;
    the beat seq is process-monotonic and advances only in the beat
    loop, so a coordinator death lands at an exact protocol boundary
    every run.  Env form: ``MXNET_FI_KILL_ON_BEAT_SEQ`` (compose with
    ``MXNET_FI_ONLY_SERVER`` / ``MXNET_FI_ONLY_COORDINATOR``)."""
    with _lock:
        _plan.kill_on_beat_seq = int(n)
    try:
        yield
    finally:
        with _lock:
            _plan.kill_on_beat_seq = None


@contextlib.contextmanager
def delay_barrier_release(ms, times=1):
    """Deterministically WEDGE the next ``times`` barrier rendezvous:
    the server sleeps ``ms`` milliseconds before registering the next
    arriving barrier request, so every other rank's park — and the
    delayed rank's own reply, hence its release — stretch by exactly
    that long.  The CPU-testable stall the ``mxnet_tpu.health``
    watchdogs exist for: no real wedge (dead peer, wedged lock) is
    needed to prove a trip fires within its budget.  Env form:
    ``MXNET_FI_STALL_BARRIER_MS`` (one stall; composes with
    ``MXNET_FI_ONLY_SERVER`` / ``MXNET_FI_ONLY_COORDINATOR``)."""
    with _lock:
        _plan.stall_barrier_s = float(ms) / 1000.0
        _plan.stall_barrier_times = int(times)
    try:
        yield
    finally:
        with _lock:
            _plan.stall_barrier_s = 0.0
            _plan.stall_barrier_times = 0


@contextlib.contextmanager
def blackhole_after_replies(n):
    """GRAY failure: serve ``n`` enveloped data-channel replies
    normally, then swallow every later one — the connection stays open,
    requests are still read and handled, heartbeats still ack, but no
    reply ever leaves.  To a liveness check the server looks perfectly
    healthy; to a caller every request stalls forever.  The stall shape
    a router's reply timeout (not its heartbeat feed) must catch.  Env
    form: ``MXNET_FI_BLACKHOLE_AFTER`` (composes with
    ``MXNET_FI_ONLY_SERVER`` / ``MXNET_FI_ONLY_COORDINATOR``)."""
    with _lock:
        _plan.blackhole_after = int(n)
        _plan.bh_seen = 0
        _plan.blackholed = 0
    try:
        yield
    finally:
        with _lock:
            _plan.blackhole_after = None
            _plan.bh_seen = 0


@contextlib.contextmanager
def shm_wedge_after_frames(n):
    """WEDGE the same-host shm lane: the leader drains ``n`` more ring
    frames normally, then stops popping — requests pile up unconsumed,
    exactly what a descheduled/deadlocked leader drain looks like.  The
    follower's stall watchdog (MXNET_KVSTORE_SHM_STALL_S) must notice
    the ring not moving and fail over to TCP via the ordinary
    reconnect-and-replay path, with zero lost envelopes — CPU-testable
    without a real hang.  Env form: ``MXNET_FI_SHM_WEDGE_AFTER``
    (composes with ``MXNET_FI_ONLY_RANK`` to target one leader)."""
    with _lock:
        _plan.shm_wedge_after = int(n)
        _plan.shm_drained = 0
        _plan.shm_wedged = 0
    try:
        yield
    finally:
        with _lock:
            _plan.shm_wedge_after = None
            _plan.shm_drained = 0


@contextlib.contextmanager
def delay_acks(seconds):
    """Sleep before every server reply (both sides keep working — this
    only stretches the ack latency, deterministically)."""
    with _lock:
        prev, _plan.delay_ack_s = _plan.delay_ack_s, float(seconds)
    try:
        yield
    finally:
        with _lock:
            _plan.delay_ack_s = prev


@contextlib.contextmanager
def refuse_connects(m):
    """Fail the next M client connect attempts with ConnectionRefused."""
    with _lock:
        _plan.refuse_connects = int(m)
    try:
        yield
    finally:
        with _lock:
            _plan.refuse_connects = 0


@contextlib.contextmanager
def refuse_accepts(m):
    """Close the next M server-accepted connections immediately."""
    with _lock:
        _plan.refuse_accepts = int(m)
    try:
        yield
    finally:
        with _lock:
            _plan.refuse_accepts = 0


# -- transport hooks (called by kvstore / kvstore_server) --------------------
def _sever(sock, point, n):
    try:
        sock.close()
    except OSError:
        pass
    raise ConnectionError(
        f"faultinject: connection killed at {point} of message #{n}")


def client_send(sock):
    """Before a data-channel message is written to the socket."""
    with _lock:
        if _plan.kill_after is None or not _rank_active():
            return
        _plan.sent += 1
        if _plan.sent != _plan.kill_after \
                or _plan.kill_point != "before_send":
            return
        _plan.kill_after = None     # fire once
        _plan.kills_fired += 1
        n = _plan.sent
    _sever(sock, "before_send", n)


def _client_post_send(sock, point):
    with _lock:
        if (_plan.kill_after is None or not _rank_active()
                or _plan.sent != _plan.kill_after
                or _plan.kill_point != point):
            return
        _plan.kill_after = None     # fire once
        _plan.kills_fired += 1
        n = _plan.sent
    _sever(sock, point, n)


def client_sent(sock):
    """After a data-channel message hit the socket (the ack-loss case:
    the server will apply the request, the client will never hear)."""
    _client_post_send(sock, "after_send")


def client_recv(sock):
    """Before blocking on a data-channel reply."""
    _client_post_send(sock, "on_recv")


def client_window(sock, unacked):
    """After a data-channel send, with the count of unacked envelopes
    currently in flight (the sliding-window depth)."""
    with _lock:
        if (_plan.kill_unacked is None or not _rank_active()
                or unacked < _plan.kill_unacked):
            return
        _plan.kill_unacked = None   # fire once
        _plan.kills_fired += 1
        n = _plan.sent
    _sever(sock, f"window_unacked[{unacked}]", n)


def client_connect(uri):
    """Before a data-channel connect/reconnect attempt."""
    with _lock:
        if _plan.refuse_connects <= 0 or not _rank_active():
            return
        _plan.refuse_connects -= 1
        _plan.connects_refused += 1
    raise ConnectionRefusedError(f"faultinject: refused connect to {uri}")


def server_accept(conn) -> bool:
    """Called with every accepted connection; True = injected refusal
    (the connection is already closed, skip serving it)."""
    with _lock:
        if _plan.refuse_accepts <= 0:
            return False
        _plan.refuse_accepts -= 1
        _plan.accepts_refused += 1
    try:
        conn.close()
    except OSError:
        pass
    return True


def server_reply_delay():
    """Called before every server reply send."""
    with _lock:
        d = _plan.delay_ack_s
    if d > 0:
        time.sleep(d)


def server_blackhole() -> bool:
    """Called before every server data-channel reply send; True =
    swallow the reply (the caller returns without writing a byte).
    Counts only the replies that reach this hook, so heartbeat acks and
    raw control replies (``fi_role=None`` sends) are exempt — exactly
    the gray-failure contract: liveness keeps answering while the
    request stream goes silent."""
    with _lock:
        if _plan.blackhole_after is None or not _server_active():
            return False
        _plan.bh_seen += 1
        if _plan.bh_seen <= _plan.blackhole_after:
            return False
        _plan.blackholed += 1
        return True


def shm_drain_gate() -> bool:
    """Called by the mesh leader's lane drain before each ring pop that
    has a frame waiting; False = the armed wedge swallows the drain
    (the ring appears stuck to the follower, whose stall watchdog then
    drives the TCP fallback).  Counts only pops that would have
    succeeded, so the wedge lands after exactly N delivered frames."""
    with _lock:
        if _plan.shm_wedge_after is None or not _rank_active():
            return True
        if _plan.shm_drained < _plan.shm_wedge_after:
            _plan.shm_drained += 1
            return True
        _plan.shm_wedged += 1
        return False


def barrier_stall():
    """Called by the server at every barrier arrival, BEFORE the
    arrival registers.  Fires the armed one-shot(s) of
    :func:`delay_barrier_release` — the sleep happens outside every
    lock, so only the stalled rendezvous (and the ranks parked on it)
    feel it."""
    with _lock:
        if _plan.stall_barrier_times <= 0 or _plan.stall_barrier_s <= 0 \
                or not _server_active():
            return
        _plan.stall_barrier_times -= 1
        d = _plan.stall_barrier_s
    time.sleep(d)


def _sigkill_self():
    """SIGKILL this process (separate function so in-process tests can
    monkeypatch the trigger without actually dying)."""
    import signal
    import sys
    sys.stdout.flush()
    sys.stderr.flush()
    os.kill(os.getpid(), signal.SIGKILL)


def server_replied():
    """Called after every ENVELOPED server reply hit the socket (raw
    messages and heartbeat pings are exempt, keeping the count
    deterministic).  Fires the armed process kill — SIGKILL, not an
    exception: elastic tests need real process death, with the served
    state genuinely lost."""
    with _lock:
        if _plan.kill_process_after is None or not _server_active():
            return
        _plan.acks_served += 1
        if _plan.acks_served < _plan.kill_process_after:
            return
        _plan.kill_process_after = None     # fire once
        _plan.kills_fired += 1
    _sigkill_self()


def server_beat(seq):
    """Called by the elastic beat loop with every beat it sends (the seq
    is process-monotonic across all peers).  Fires the armed beat-
    boundary SIGKILL — real process death at an exact beat number, the
    deterministic way to kill a COORDINATOR whose enveloped-ack count
    is timing-dependent (it serves barrier rendezvous)."""
    with _lock:
        if _plan.kill_on_beat_seq is None or not _server_active():
            return
        if int(seq) < _plan.kill_on_beat_seq:
            return
        _plan.kill_on_beat_seq = None       # fire once
        _plan.kills_fired += 1
    _sigkill_self()


def _arm_from_env():
    """One-shot env activation (multi-process tests: the launcher can't
    reach into a worker, but its environment can)."""
    ka = os.environ.get("MXNET_FI_KILL_AFTER")
    ku = os.environ.get("MXNET_FI_KILL_UNACKED")
    rc = os.environ.get("MXNET_FI_REFUSE_CONNECTS")
    ra = os.environ.get("MXNET_FI_REFUSE_ACCEPTS")
    dl = os.environ.get("MXNET_FI_DELAY_ACK_MS")
    kp = os.environ.get("MXNET_FI_KILL_PROCESS_AFTER")
    kb = os.environ.get("MXNET_FI_KILL_ON_BEAT_SEQ")
    sb = os.environ.get("MXNET_FI_STALL_BARRIER_MS")
    bh = os.environ.get("MXNET_FI_BLACKHOLE_AFTER")
    sw = os.environ.get("MXNET_FI_SHM_WEDGE_AFTER")
    orank = os.environ.get("MXNET_FI_ONLY_RANK")
    osrv = os.environ.get("MXNET_FI_ONLY_SERVER")
    ocoord = os.environ.get("MXNET_FI_ONLY_COORDINATOR")
    if not (ka or ku or rc or ra or dl or kp or kb or sb or bh or sw):
        return
    configure(
        kill_after=int(ka) if ka else None,
        kill_point=os.environ.get("MXNET_FI_KILL_POINT", "before_send"),
        kill_unacked=int(ku) if ku else None,
        delay_ack_s=float(dl) / 1000.0 if dl else 0.0,
        refuse_connects=int(rc) if rc else 0,
        refuse_accepts=int(ra) if ra else 0,
        only_rank=int(orank) if orank else None,
        kill_process_after=int(kp) if kp else None,
        only_server=int(osrv) if osrv else None,
        only_coordinator=bool(ocoord) and
        ocoord.lower() not in ("0", "false", "off", ""),
        kill_on_beat_seq=int(kb) if kb else None,
        stall_barrier_s=float(sb) / 1000.0 if sb else 0.0,
        blackhole_after=int(bh) if bh else None,
        shm_wedge_after=int(sw) if sw else None)


_arm_from_env()
